"""ResilientBackend: retry-wrapped dispatch + the degradation ladder.

The graceful-degradation ladder is the ISSUE-5 survival contract: a
mining run whose fused/pallas kernel starts failing steps down to the
jnp sweep, and a run whose device dispatch is gone entirely steps down
to the native CPU miner — emitting a ``backend_degraded`` event + gauge
and *continuing to mine* instead of crashing. Every rung implements the
same deterministic lowest-nonce contract, so a degraded chain is
byte-identical to the chain the dead rung would have mined (the
equivalence suite's guarantee doing resilience work).

Trust boundary: a backend result is never taken on faith. Any returned
winner is re-validated host-side (recompute sha256d, check the
difficulty and the reported digest) — two compressions per *block*, not
per nonce — so a corrupt device result (bitflip, injected fault, broken
kernel) surfaces as a retryable ``CorruptResult`` at the policy layer
instead of poisoning the C++ Node. ``ConfigError`` is exempt from both
retry and degradation: an explicit ``--kernel pallas`` off-TPU must
keep failing loudly (the CLI's clean-error contract), never silently
step down.
"""
from __future__ import annotations

import concurrent.futures
import threading
from typing import Callable

from .. import core
from ..backend import MinerBackend, SearchResult
from ..config import ConfigError
from ..telemetry import counter, gauge
from ..telemetry.events import emit_event
from . import RetryExhausted
from .policy import RetryPolicy, call_with_retry, policy_for


class CorruptResult(RuntimeError):
    """A backend returned a winner that fails host-side re-validation."""


Rung = tuple[str, Callable[[], MinerBackend]]


def ladder_from_config(config, cpu_ranks: int | None = None,
                       mesh=None) -> list[Rung]:
    """The degradation ladder a MinerConfig implies, top rung first:
    requested device kernel → jnp sweep → native CPU miner. A cpu
    config has the single native rung (retry-only, nothing to degrade
    to). Factories are lazy: a dead rung's replacement is only built
    when the ladder steps down onto it."""
    from ..backend import get_backend

    n_ranks = config.n_miners if cpu_ranks is None else cpu_ranks

    def cpu_factory():
        return get_backend("cpu", n_ranks=n_ranks,
                           batch_size=config.batch_size)

    if config.backend == "cpu":
        return [("cpu", cpu_factory)]

    def tpu_factory(kernel):
        return lambda: get_backend("tpu",
                                   batch_pow2=config.effective_batch_pow2,
                                   n_miners=config.n_miners,
                                   kernel=kernel, mesh=mesh)

    rungs: list[Rung] = [(f"tpu:{config.kernel}",
                          tpu_factory(config.kernel))]
    if config.kernel != "jnp":
        rungs.append(("tpu:jnp", tpu_factory("jnp")))
    rungs.append(("cpu", cpu_factory))
    return rungs


class ResilientBackend(MinerBackend):
    """Wraps a ladder of backends behind the MinerBackend contract.

    The top rung is constructed eagerly so construction-time config
    errors (oversubscribed mesh, unknown backend) surface exactly where
    they did before the wrap. ``name`` reflects the ACTIVE rung, so
    metric labels and run summaries report what actually mined.
    """

    def __init__(self, rungs: list[Rung],
                 policy: RetryPolicy | None = None, seed: int = 0):
        if not rungs:
            raise ConfigError("degradation ladder needs at least one rung")
        self._rungs = list(rungs)
        self._i = 0
        self._backend = rungs[0][1]()
        self._policy = policy
        self._seed = seed
        self.degradations: list[dict] = []
        # Single-flight discipline for the async pipeline: every search
        # (sync caller or dispatch worker) runs under this lock, so the
        # ladder state (_i/_backend) is only ever stepped by ONE dispatch
        # at a time — a speculative dispatch that exhausts its rung
        # degrades the ladder exactly once, and the next dispatch starts
        # on the surviving rung instead of racing a half-rebuilt one.
        # chainlint deadlint holds this shape: THR002 accepts
        # _step_down's unlocked writes because its every call site is
        # lock-held (the one-hop rule), and LCK treats the RLock's
        # re-acquisition as reentrancy, not an inversion.
        self._lock = threading.RLock()
        self._worker: concurrent.futures.ThreadPoolExecutor | None = None

    # ---- introspection ---------------------------------------------------

    @property
    def name(self) -> str:          # type: ignore[override]
        return self._backend.name

    @property
    def rung(self) -> str:
        return self._rungs[self._i][0]

    @property
    def degraded(self) -> bool:
        return self._i > 0

    @property
    def active_backend(self) -> MinerBackend:
        return self._backend

    # ---- the plugin contract ---------------------------------------------

    def search(self, header80: bytes, difficulty_bits: int,
               start_nonce: int = 0,
               max_count: int = 1 << 32) -> SearchResult:
        with self._lock:
            while True:
                label = self.rung
                try:
                    return call_with_retry(
                        lambda: self._checked_search(header80,
                                                     difficulty_bits,
                                                     start_nonce,
                                                     max_count),
                        site=f"dispatch.{label}",
                        policy=(self._policy if self._policy is not None
                                else policy_for("dispatch",
                                                seed=self._seed)))
                except RetryExhausted as e:
                    if not self._step_down(e):
                        raise

    def search_async(self, header80: bytes, difficulty_bits: int,
                     start_nonce: int = 0,
                     max_count: int = 1 << 32
                     ) -> "concurrent.futures.Future":
        """The real async dispatch seam: submits the FULL resilient
        search (retry budget, host-side re-validation, ladder
        step-down) to the backend's one dispatch worker. One worker =
        FIFO completion AND single-flight degradation: a speculative
        dispatch retries/degrades to completion before the next
        dispatch starts, so it can never poison an in-flight one — the
        ladder the survivor lands on is simply the ladder every later
        dispatch (speculative or not) inherits."""
        with self._lock:
            if self._worker is None:
                self._worker = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="dispatch-worker")
            worker = self._worker
        return worker.submit(self.search, header80, difficulty_bits,
                             start_nonce=start_nonce, max_count=max_count)

    def _checked_search(self, header80: bytes, difficulty_bits: int,
                        start_nonce: int, max_count: int) -> SearchResult:
        res = self._backend.search(header80, difficulty_bits,
                                   start_nonce=start_nonce,
                                   max_count=max_count)
        if res.nonce is not None:
            digest = core.header_hash(core.set_nonce(header80, res.nonce))
            if core.leading_zero_bits(digest) < difficulty_bits or \
                    (res.hash is not None and res.hash != digest):
                counter("corrupt_results_total",
                        help="backend winners that failed host-side "
                             "re-validation", backend=self._backend.name
                        ).inc()
                raise CorruptResult(
                    f"{self.rung}: nonce {res.nonce} fails re-validation "
                    f"(difficulty {difficulty_bits})")
        return res

    def _step_down(self, err: RetryExhausted) -> bool:
        """Advances to the next constructible rung; False when the
        ladder is exhausted (the caller re-raises — CLI rc 2)."""
        while self._i + 1 < len(self._rungs):
            old = self.rung
            self._i += 1
            label, factory = self._rungs[self._i]
            try:
                self._backend = factory()
            except Exception as e:
                # A rung whose CONSTRUCTION fails (jax gone, mesh dead)
                # is skipped loudly; the ladder keeps walking down.
                emit_event({"event": "backend_rung_unavailable",
                            "rung": label,
                            "error": f"{type(e).__name__}: {e}"})
                continue
            record = {"event": "backend_degraded", "from": old,
                      "to": label, "rung_index": self._i,
                      "error": str(err)}
            self.degradations.append(record)
            counter("backend_degradations_total",
                    help="ladder step-downs after exhausted retries").inc()
            gauge("backend_degraded",
                  help="active degradation-ladder rung index "
                       "(0 = requested backend)").set(self._i)
            emit_event(record)
            return True
        return False
