"""Resilience: deterministic fault injection, retry/fallback dispatch,
and crash-safe recovery (ISSUE 5; arxiv 1804.08230 §worker failure,
VaultxGPU's recovery-first consensus design).

Four PRs built eyes (chainlint, telemetry, forensics, perfwatch); this
package keeps the system ALIVE long enough for those eyes to matter:

* **faultplan** — a seeded, byte-reproducible fault-plan spec
  (``FaultPlan``): which injection site, which call index, which fault
  class (``raise`` / ``hang`` / ``corrupt`` / ``partial``). Armed via
  ``--fault-plan PATH|seed:N`` (env ``MPIBT_FAULT_PLAN``) on
  mine/sim/bench, so any failure mode replays byte-for-byte.
* **injection** — the process-global arming point. Hooks threaded into
  backend dispatch (``backend/tpu.py`` / ``backend/cpu.py``), the
  simulation bus (``simulation.Network.deliver_due``), native-lib load
  (``core/build.py``), checkpoint I/O (``utils/checkpoint.py``) and
  distributed init call ``injection.check(site)`` and either crash,
  wedge, or hand back a fault for site-specific damage.
* **policy** — capped exponential backoff with deterministic jitter
  (seeded, no global RNG), per-layer budgets, and ``RetryExhausted``
  as the one loud give-up signal (CLI rc 2).
* **dispatch** — ``ResilientBackend``: the graceful-degradation ladder
  fused/pallas kernel → jnp sweep → native CPU miner. Every returned
  winner is re-validated host-side (two SHA-256 compressions), so a
  corrupt device result is a *detected* fault, not a poisoned chain.
  Degradation emits a ``backend_degraded`` event + gauge and keeps
  mining instead of crashing.

Crash-safe checkpointing lives in ``utils/checkpoint.py`` (atomic
write + length/SHA-256 trailer + torn-tail recovery); the chaos gate is
``python -m mpi_blockchain_tpu.resilience smoke`` (``make chaos-smoke``).
Semantics: docs/resilience.md. Standard library only — importing this
package never pulls in jax.
"""
from __future__ import annotations

from ..config import ConfigError


class FaultInjected(RuntimeError):
    """An injected fault fired (kind=raise, or site-specific damage that
    surfaces as an exception). Carries the site/kind for forensics."""

    def __init__(self, site: str, kind: str, message: str = ""):
        self.site = site
        self.kind = kind
        super().__init__(message or f"injected fault at {site} ({kind})")


class FaultTimeout(FaultInjected):
    """A simulated hang exceeded its watchdog budget (kind=hang)."""


class FaultPlanError(ConfigError):
    """Invalid or unexhausted fault plan (CLI rc 3): unparseable spec,
    unknown site/kind, or — under ``strict`` — faults that never fired."""


class RankLossSuspected(RuntimeError):
    """A guarded collective/rendezvous exceeded its watchdog budget (or
    an injected ``parallel.collective`` fault fired): a peer rank is
    suspected dead and the survivor must consult the meshwatch oracle
    and shrink instead of hanging forever (resilience/elastic.py)."""

    def __init__(self, site: str, elapsed_s: float | None = None,
                 message: str = ""):
        self.site = site
        self.elapsed_s = elapsed_s
        super().__init__(
            message or f"collective at {site} exceeded its watchdog"
            + (f" after {elapsed_s:.3f}s" if elapsed_s is not None else "")
            + " — peer rank loss suspected")


class RetryExhausted(RuntimeError):
    """A policy-wrapped call failed on every attempt and every ladder
    rung below it (CLI rc 2). ``last`` keeps the final cause."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        self.site = site
        self.attempts = attempts
        self.last = last
        super().__init__(f"{site}: exhausted {attempts} attempt(s); "
                         f"last error: {type(last).__name__}: {last}")


from .faultplan import FaultPlan, FaultSpec  # noqa: E402,F401
from .policy import RetryPolicy, call_with_retry, policy_for  # noqa: E402,F401
