"""Retry/timeout/backoff policy: capped exponential backoff with
deterministic jitter, per-layer budgets, one loud give-up signal.

Design constraints:

* **Deterministic.** Jitter derives from (site, attempt, seed) through
  crc32 — the same faulted run schedules the same sleeps, so replaying
  a fault plan replays the recovery timeline too. No global RNG.
* **Budgeted per layer.** Device dispatch, distributed init, and
  checkpoint I/O fail differently (a wedged TPU init deserves more
  patience than a torn local write); ``policy_for(site)`` carries the
  per-layer table, and ``MPIBT_MAX_RETRIES`` caps attempts globally
  for operators who want fail-fast CI.
* **Selective.** ``ConfigError`` (and KeyboardInterrupt/SystemExit)
  are never retried: a misconfiguration does not heal with backoff,
  and retrying it would bury the clean CLI error contract.

``call_with_retry`` is the ONE sanctioned swallow point for dispatch/IO
exceptions — chainlint rule RES001 flags ad-hoc ``except Exception:
pass`` swallowing anywhere else in those paths.
"""
from __future__ import annotations

import dataclasses
import struct
import time
import zlib

from ..config import ConfigError
from . import RetryExhausted

#: Operator cap on attempts for every site (env; min 1 attempt).
_ENV_MAX_ATTEMPTS = "MPIBT_MAX_RETRIES"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with deterministic, seeded jitter."""
    max_attempts: int = 3        # total tries (first call included)
    base_backoff_s: float = 0.01
    max_backoff_s: float = 0.25
    seed: int = 0

    def backoff_s(self, site: str, attempt: int) -> float:
        """Sleep before retry #attempt (0-based): capped exponential,
        jittered into [cap/2, cap) by crc32(site, attempt, seed) — the
        decorrelation real backoff needs, reproducible anyway."""
        cap = min(self.base_backoff_s * (2 ** attempt), self.max_backoff_s)
        key = site.encode() + struct.pack("<Ii", attempt, self.seed)
        frac = zlib.crc32(key) % 1024 / 1024.0
        return cap * (0.5 + 0.5 * frac)


#: Per-layer budgets (docs/resilience.md). Dispatch failures are cheap
#: to retry and cheap to degrade past; distributed init is expensive to
#: abandon (the whole world restarts), so it gets the longest leash;
#: checkpoint I/O retries cover transient FS errors only — integrity
#: failures are CheckpointError (a ConfigError: never retried).
_PER_SITE: dict[str, RetryPolicy] = {
    "dispatch": RetryPolicy(max_attempts=3, base_backoff_s=0.01,
                            max_backoff_s=0.25),
    "distributed.init": RetryPolicy(max_attempts=4, base_backoff_s=0.25,
                                    max_backoff_s=2.0),
    "checkpoint.write": RetryPolicy(max_attempts=2, base_backoff_s=0.02,
                                    max_backoff_s=0.1),
    "checkpoint.read": RetryPolicy(max_attempts=2, base_backoff_s=0.02,
                                   max_backoff_s=0.1),
    # Rebuilding the miner mesh over the survivors of a rank loss: a
    # short leash — the elastic supervisor must either shrink quickly or
    # give up loudly, not camp on a fabric that keeps wedging.
    "mesh.rebuild": RetryPolicy(max_attempts=2, base_backoff_s=0.05,
                                max_backoff_s=0.5),
    # The serving front door ("service" covers service.submit and
    # service.rebuild via the layer-prefix fallback): a tight leash
    # with near-zero backoff — a request holds an HTTP handler thread
    # while it retries, so the budget must resolve well inside the
    # per-request deadline and shed typed rather than camp.
    "service": RetryPolicy(max_attempts=2, base_backoff_s=0.005,
                           max_backoff_s=0.02),
}
_DEFAULT = RetryPolicy()

#: Site-specific attempt knobs (docs/resilience.md): unlike the global
#: MPIBT_MAX_RETRIES cap these can RAISE a site's budget too (an 8-chip
#: bring-up may want more mesh-rebuild patience than CI's default 2).
_SITE_ENV_ATTEMPTS = {"mesh.rebuild": "MPIBT_MESH_REBUILD_RETRIES"}


def policy_for(site: str, seed: int = 0) -> RetryPolicy:
    """The per-layer budget for a site; dotted sites fall back to their
    layer prefix (``dispatch.tpu:jnp`` -> ``dispatch``)."""
    from ..telemetry.events import env_number

    base = _PER_SITE.get(site) or _PER_SITE.get(site.split(".", 1)[0],
                                                _DEFAULT)
    attempts = base.max_attempts
    site_env = _SITE_ENV_ATTEMPTS.get(site) \
        or _SITE_ENV_ATTEMPTS.get(site.split(".", 1)[0])
    if site_env:
        attempts = env_number(site_env, attempts, cast=int, minimum=1)
    cap = env_number(_ENV_MAX_ATTEMPTS, None, cast=int, minimum=1)
    attempts = attempts if cap is None else min(attempts, cap)
    if attempts == base.max_attempts and seed == base.seed:
        return base
    return dataclasses.replace(base, max_attempts=attempts, seed=seed)


NO_RETRY = (ConfigError, KeyboardInterrupt, SystemExit)


def call_with_retry(fn, *, site: str, policy: RetryPolicy | None = None,
                    sleep=time.sleep):
    """Calls ``fn()`` under the site's retry budget.

    Transient failures sleep the deterministic backoff and retry; the
    final failure raises ``RetryExhausted`` (chaining the cause).
    ``ConfigError`` propagates immediately — misconfiguration is not a
    fault, and the CLI's clean-error contract depends on seeing it.
    """
    from ..telemetry import counter
    from ..telemetry.events import emit_event

    policy = policy if policy is not None else policy_for(site)
    last: BaseException | None = None
    for attempt in range(max(1, policy.max_attempts)):
        try:
            return fn()
        except NO_RETRY:
            raise
        except Exception as e:
            last = e
            if attempt + 1 >= policy.max_attempts:
                break
            counter("retries_total",
                    help="policy-layer retries after a transient failure",
                    site=site).inc()
            emit_event({"event": "retry", "site": site,
                        "attempt": attempt + 1,
                        "of": policy.max_attempts,
                        "error": f"{type(e).__name__}: {e}"})
            sleep(policy.backoff_s(site, attempt))
    raise RetryExhausted(site, max(1, policy.max_attempts), last) from last
