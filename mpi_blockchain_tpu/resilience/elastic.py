"""Elastic mesh: rank-death survival — detect, shrink, re-stripe, mine on.

Every multi-rank path used to die with its weakest rank: meshwatch
(docs/observability.md §Mesh shards) *names* dead ranks and the
resilience ladder degrades *backends*, but nothing degraded the *mesh* —
a SIGKILL'd peer left survivors blocked inside the ``winner_select``
psum/pmin rendezvous forever (the hang class chainlint SPMD003 flags
statically). This module closes that gap with three pieces:

* **guarded_collective** — the watchdogged dispatch every elastic
  rendezvous goes through (chainlint SPMD004 enforces this over
  ``elastic_files``): the collective runs on a daemon worker thread
  under ``MPIBT_COLLECTIVE_TIMEOUT``; exceeding it raises
  ``RankLossSuspected`` instead of hanging the survivor. The wedged
  dispatch thread is jettisoned with its mesh — the supervisor rebuilds
  a fresh one. The ``parallel.collective`` fault site makes a dying
  rendezvous deterministic (every kind surfaces as suspicion: a hung,
  raised, or damaged collective are all indistinguishable from a lost
  peer at this boundary).

* **ElasticWorld + ElasticMiner** — the process-per-rank world (the
  ``mpirun -np N`` launch shape, one OS process per rank, shared
  ``--mesh-obs`` directory, NO jax.distributed — a jax world pins its
  size at init and cannot shrink). Each rank sweeps only its stripe of
  the nonce space (``parallel.mesh.stripe_windows`` — the host twin of
  ``sharded_local_base``); between blocks the supervisor consults the
  meshwatch shard directory: the PR-7 asymmetry detector (a finished
  rank wrote a final shard, a SIGKILL'd one could not) is the death
  oracle — no new coordinator, no timeout guessing. Confirmed-dead
  ranks (``recommended_action == "evict"``) are evicted and the stripes
  re-striped over the survivors with no gap and no overlap (the
  property tests/test_elastic.py pins for every world_size <= 8 x
  dead-subset pair). Membership rides the crash-safe checkpoint
  sidecar, so ``--resume`` restores the shrunken world, not the seed
  world. The ``mesh.rank_death`` fault site hard-exits a seeded-chosen
  victim (``os._exit`` — no final shard, exactly like SIGKILL) while
  every survivor evicts it at the same block step, which is what makes
  the whole recovery byte-reproducible (same-seed runs produce
  byte-identical causal dumps).

* **ElasticMeshBackend** — the in-process device-mesh flavor (one
  process, n_miners chips — the v5e8 launch shape): every sharded
  dispatch (the XLA program containing the psum/pmin winner-select)
  runs under the guard; on suspicion the mesh is rebuilt one device
  smaller under the ``mesh.rebuild`` retry budget and mining continues.
  One process writes one shard, so there is no per-device staleness
  asymmetry to consult here — the watchdog itself is the detector, and
  the lowest-nonce determinism contract makes the shrunken mesh mine
  the byte-identical chain (n_miners-invariance, BASELINE.md).

Importing this module never pulls in jax (the resilience-package
contract); the striping math and mesh builds are imported lazily.
"""
from __future__ import annotations

import os
import queue
import re
import struct
import threading
import time
import zlib

from ..config import ConfigError
from ..models.miner import Miner
from ..telemetry import CausalLog, counter, emit_event, gauge
from ..telemetry.causal import dump_causal_logs
from ..telemetry.events import env_number
from . import FaultInjected, RankLossSuspected
from .policy import call_with_retry

#: Watchdog budget for one guarded collective/rendezvous (seconds). A
#: healthy winner-select dispatch completes in milliseconds-to-seconds;
#: a peer death leaves it blocked in the fabric forever — 60 s is "the
#: mesh is gone", not "the mesh is slow".
DEFAULT_COLLECTIVE_TIMEOUT_S = env_number(
    "MPIBT_COLLECTIVE_TIMEOUT", 60.0, cast=float, minimum=1e-3)

#: Startup grace before a MISSING rank (expected by world_size, never
#: wrote a shard) becomes evictable. Dead-shard/failed evictions need no
#: grace — a shard existed, the asymmetry is proven — but "missing" at
#: startup usually just means "still importing jax", and evicting a
#: late-arriving rank would make it re-overlap stripes the survivors
#: re-covered once it finally joins.
DEFAULT_MISSING_GRACE_S = env_number(
    "MPIBT_ELASTIC_GRACE", 15.0, cast=float, minimum=0.0)


class _GuardWorker:
    """One long-lived daemon worker ``guarded_collective`` dispatches
    on. Workers are pooled and reused — a striped elastic miner routes
    EVERY window sweep through the guard, so a thread spawn per
    dispatch would sit on the hot path the HOTPATH lint protects. A
    worker whose dispatch timed out is ABANDONED (never returned to the
    pool): it is still parked inside the wedged fn, and its eventual
    reply lands in a per-dispatch queue nobody reads."""

    def __init__(self):
        self.inbox: queue.Queue = queue.Queue(maxsize=1)
        self.thread = threading.Thread(target=self._loop,
                                       name="guarded-collective",
                                       daemon=True)
        self.thread.start()

    def _loop(self) -> None:
        while True:
            # The idle park between dispatches: a sanctioned FUT002
            # waiter seam (the watchdog guards the DISPATCH, not this
            # daemon worker waiting for work).
            fn, out = self.inbox.get()
            try:
                out.put(("ok", fn()))
            except BaseException as e:   # delivered to the caller
                out.put(("err", e))


_idle_workers: list[_GuardWorker] = []
_idle_lock = threading.Lock()


def guarded_collective(fn, *, site: str = "winner_select",
                       timeout_s: float | None = None):
    """Runs ``fn()`` — a collective/rendezvous dispatch — under the
    rank-loss watchdog. The ONE sanctioned way elastic code reaches a
    collective (chainlint SPMD004).

    The dispatch runs on a pooled daemon worker thread; if it does not
    return within ``timeout_s`` (``MPIBT_COLLECTIVE_TIMEOUT``), the
    survivor raises ``RankLossSuspected`` instead of blocking forever.
    The abandoned worker stays parked in the dead fabric — it is
    daemonic and its mesh is about to be rebuilt, so it leaks nothing
    the process needs. Exceptions from ``fn`` re-raise unchanged. The
    ``parallel.collective`` fault site fires here: every kind surfaces
    as ``RankLossSuspected`` (a hung, raised, or damaged rendezvous
    are the same event to the survivor).
    """
    from . import injection

    timeout_s = (DEFAULT_COLLECTIVE_TIMEOUT_S if timeout_s is None
                 else float(timeout_s))
    try:
        fault = injection.check("parallel.collective", collective=site)
    except FaultInjected as e:
        raise RankLossSuspected(
            site, message=f"injected fault in the {site} rendezvous: "
            f"{e}") from e
    if fault is not None:
        raise RankLossSuspected(
            site, message=f"injected {fault.kind} fault damaged the "
            f"{site} rendezvous — treating as peer loss")
    with _idle_lock:
        worker = _idle_workers.pop() if _idle_workers else None
    if worker is None:
        worker = _GuardWorker()
    worker.thread.name = f"guarded-{site}"
    out: queue.Queue = queue.Queue(maxsize=1)
    t0 = time.monotonic()
    try:
        # The wait is a `collective` pipeline segment on the newest
        # dispatch record (stamped with the in-scope block trace), so
        # the per-block critical path can price rendezvous waits
        # separately from device compute — mesh builds/rebuilds happen
        # outside any device window and would otherwise read as gap.
        # Recorded even when the wait times out: that overhang is
        # exactly the wait worth seeing. The skew_span wraps the whole
        # dispatch (put + wait) so its enter stamp is this rank's
        # ARRIVAL at the rendezvous — the quantity the mesh-skew
        # analyzer joins across ranks — and a timeout exits the span
        # with ok=False before the suspicion is raised.
        from ..meshprof.spans import skew_span
        from ..meshwatch.pipeline import profiler

        # chained=False: the wait runs CONCURRENTLY with whatever the
        # record's open stage is — backdating it to the previous stage
        # boundary (the chained default) would stretch it over the
        # whole device window.
        with skew_span(site=site):
            worker.inbox.put((fn, out))
            with profiler().segment_on_last("collective", chained=False):
                kind, value = out.get(timeout=timeout_s)
    except queue.Empty:
        elapsed = time.monotonic() - t0
        counter("collective_timeouts_total",
                help="guarded collectives that exceeded the rank-loss "
                     "watchdog", site=site).inc()
        emit_event({"event": "collective_timeout", "site": site,
                    "elapsed_s": round(elapsed, 3),
                    "timeout_s": timeout_s})
        raise RankLossSuspected(site, elapsed_s=elapsed) from None
    with _idle_lock:
        _idle_workers.append(worker)
    if kind == "err":
        raise value
    return value


# ---- the death oracle ------------------------------------------------------


def confirmed_dead(obs_dir, live, self_rank: int, *,
                   stall_s: float | None = None,
                   heartbeat_stall_s: float | None = None,
                   allow_missing: bool = False,
                   now: float | None = None) -> list[tuple[int, str]]:
    """Ranks among ``live`` the meshwatch shard directory CONFIRMS dead:
    ``recommended_action == "evict"`` (dead-shard stale, failed, or —
    only when ``allow_missing`` — expected-but-absent). A wedged-but-
    alive rank (``no-progress``) reads ``restart``, never ``evict``:
    evicting a rank that later recovers would re-overlap its stripes.
    Returns ``(rank, reason)`` pairs; ``self_rank`` is never returned
    (a rank does not evict itself)."""
    from ..meshwatch.aggregate import rank_status, read_shards

    status = rank_status(read_shards(obs_dir), stall_s=stall_s,
                         heartbeat_stall_s=heartbeat_stall_s, now=now)
    dead: list[tuple[int, str]] = []
    for rank in live:
        if rank == self_rank:
            continue
        info = status["ranks"].get(str(rank))
        if info is None:
            # Beyond every shard's declared world: same as missing.
            if allow_missing:
                dead.append((rank, "missing"))
            continue
        if info.get("recommended_action") != "evict":
            continue
        if info["status"] == "missing" and not allow_missing:
            continue   # startup grace: a late-arriving rank is not dead
        dead.append((rank, info.get("stale_reason") or info["status"]))
    return dead


# ---- the process-per-rank elastic world ------------------------------------


class ElasticWorld:
    """Live-membership supervisor for one rank of a process-per-rank
    elastic world.

    Tracks which ranks are live, evicts confirmed-dead peers (meshwatch
    staleness oracle + the deterministic ``mesh.rank_death`` fault
    site), exposes the re-striped nonce windows, and records every
    membership transition in a Lamport causal log (no wall clock — the
    byte-identical-dump determinism contract, same as the sim bus).
    """

    def __init__(self, world_size: int, rank: int, obs_dir=None, *,
                 stall_s: float | None = None,
                 heartbeat_stall_s: float | None = None,
                 hard_exit=os._exit):
        world_size = int(world_size)
        rank = int(rank)
        if world_size < 1:
            raise ConfigError(f"elastic world_size must be >= 1, "
                              f"got {world_size}")
        if not 0 <= rank < world_size:
            raise ConfigError(f"elastic rank {rank} out of range for "
                              f"world_size {world_size}")
        self.world_size = world_size
        self.rank = rank
        self.obs_dir = obs_dir
        self.live: list[int] = list(range(world_size))
        self.evicted: list[dict] = []
        self.log = CausalLog(rank)
        self._stall_s = stall_s
        self._hb_stall_s = heartbeat_stall_s
        self._started = time.monotonic()
        self._death_draws = 0
        # Ranks killed by fired mesh.rank_death faults — the draw pool
        # for the next victim is the seed world minus this set, NEVER
        # the oracle-mutated self.live (see _victim_for).
        self._death_victims: set[int] = set()
        self._hard_exit = hard_exit
        gauge("mesh_live_ranks",
              help="ranks with a fresh, non-final shard").set(world_size)

    # -- membership --------------------------------------------------------

    @property
    def n_live(self) -> int:
        return len(self.live)

    def index(self) -> int:
        """This rank's dense index among the survivors — the stripe
        slot ``parallel.mesh.stripe_windows`` assigns."""
        return self.live.index(self.rank)

    def stripe_windows(self, batch_size: int, space: int | None = None):
        """This rank's current nonce windows (re-striped over the
        surviving world; union over survivors = the whole space, no gap,
        no overlap). Lazy import: the striping rule lives next to
        ``sharded_local_base`` in parallel/mesh.py so the host and
        device stripings cannot drift."""
        from ..parallel.mesh import NONCE_SPACE, stripe_windows

        return stripe_windows(self.index(), self.n_live, batch_size,
                              NONCE_SPACE if space is None else space)

    def evict(self, rank: int, reason: str, height: int = 0) -> bool:
        """Removes ``rank`` from the live set (idempotent; a rank never
        evicts itself) and re-stripes: emits the ``mesh_shrunk``
        event + causal record, bumps ``mesh_evicted_ranks_total`` and
        re-stamps ``mesh_live_ranks``."""
        rank = int(rank)
        if rank == self.rank or rank not in self.live:
            return False
        self.live.remove(rank)
        self.evicted.append({"rank": rank, "reason": reason,
                             "height": height})
        counter("mesh_evicted_ranks_total",
                help="ranks evicted from the elastic mesh, by reason",
                reason=reason).inc()
        gauge("mesh_live_ranks",
              help="ranks with a fresh, non-final shard").set(self.n_live)
        self.log.record("mesh_shrunk", step=height, evicted=rank,
                        reason=reason, live=list(self.live))
        emit_event({"event": "mesh_shrunk", "rank": self.rank,
                    "evicted": rank, "reason": reason, "height": height,
                    "live": list(self.live)})
        # The chainwatch seam: an eviction is definitive membership
        # damage, so the watchdog fires its ``stale_rank`` incident NOW
        # (with the surviving membership for the bundle) instead of
        # waiting for the next cadence tick to read the ring. Lazy
        # import + flag-check no-op while disarmed/off.
        from .. import chainwatch

        chainwatch.notify_eviction(rank, reason, height=height,
                                   live=self.live)
        return True

    # -- the per-block supervision point -----------------------------------

    def step(self, height: int) -> None:
        """Once per block, BEFORE the sweep: the deterministic
        ``mesh.rank_death`` fault site first (all ranks step in lockstep
        per height, so a seeded victim choice agrees everywhere), then
        the wall-clock staleness oracle. The step is a skew span: every
        rank passes here exactly once per height in the same order, so
        (``block.step``, round) is the cross-PROCESS join key the
        mesh-skew analyzer aligns a process-per-rank world on — the
        rendezvous-equivalent of ``winner_select`` for a world with no
        in-process collective."""
        from ..meshprof.spans import skew_span

        with skew_span(site="block.step"):
            self._check_rank_death(height)
            self._poll_oracle(height)

    def _check_rank_death(self, height: int) -> None:
        from . import injection

        fault = injection.check("mesh.rank_death", height=height,
                                rank=self.rank)
        if fault is None:
            return
        victim = self._victim_for(fault)
        if victim is None:
            return
        if victim == self.rank:
            # Die like SIGKILL: no finally blocks, no final shard — the
            # survivors' oracle (or the shared plan) must notice, which
            # is the point. The injectable seam exists for tests only.
            self.log.record("rank_death", step=height, rank=victim)
            emit_event({"event": "rank_death", "rank": victim,
                        "height": height})
            self._hard_exit(137)
            return
        self.evict(victim, "rank_death", height)

    def _victim_for(self, fault) -> int | None:
        """The rank the fired ``mesh.rank_death`` fault kills: an
        explicit ``message="rank=N"`` wins; otherwise a crc32 draw from
        (plan seed, firing index) over the SEED world minus prior
        rank_death victims, EXCLUDING the lowest such rank — the anchor
        rank owns the chain artifact and the causal dump, and killing
        the observer is a different scenario. The draw deliberately
        ignores oracle evictions (``self.live``): every rank arms the
        same plan and steps this site in lockstep, but wall-clock oracle
        polls land at different instants per rank, so a draw over the
        oracle-mutated live list could pick DIFFERENT victims on
        different ranks — two ranks dying, or a still-live rank being
        evicted while it keeps mining. Drawing a victim the oracle
        already evicted is harmless: ``evict`` is a no-op then."""
        from . import injection

        m = re.search(r"rank=(\d+)", fault.message or "")
        if m:
            victim = int(m.group(1))
            if not (0 <= victim < self.world_size) \
                    or victim in self._death_victims:
                return None
            self._death_victims.add(victim)
            return victim
        candidates = sorted(set(range(self.world_size))
                            - self._death_victims)[1:]
        if not candidates:
            return None
        plan = injection.armed_plan()
        seed = plan.seed if plan is not None else 0
        key = struct.pack("<ii", int(seed), self._death_draws)
        self._death_draws += 1
        victim = candidates[zlib.crc32(b"mesh.rank_death" + key)
                            % len(candidates)]
        self._death_victims.add(victim)
        return victim

    def _poll_oracle(self, height: int) -> None:
        if not self.obs_dir:
            return
        # Startup grace for MISSING ranks: a peer is only evictable for
        # never having written a shard once this rank has itself been up
        # longer than max(stall budget, MPIBT_ELASTIC_GRACE).
        from ..meshwatch.aggregate import DEFAULT_MESH_STALL_S

        stall = (self._stall_s if self._stall_s is not None
                 else DEFAULT_MESH_STALL_S)
        grace_over = (time.monotonic() - self._started) > \
            max(stall, DEFAULT_MISSING_GRACE_S)
        for rank, reason in confirmed_dead(
                self.obs_dir, list(self.live), self.rank,
                stall_s=self._stall_s,
                heartbeat_stall_s=self._hb_stall_s,
                allow_missing=grace_over):
            self.evict(rank, reason, height)

    # -- checkpointed membership -------------------------------------------

    def membership(self) -> dict:
        """The sidecar payload that rides the crash-safe checkpoint
        (utils/checkpoint.save_chain ``mesh=``): enough to restore a
        shrunken world on ``--resume``."""
        return {"world_size": self.world_size, "live": list(self.live),
                "evicted": [dict(e) for e in self.evicted]}

    def restore(self, mesh: dict | None) -> None:
        """Adopts a checkpointed membership (the ``--resume`` path): the
        resumed run starts from the shrunken world, not the seed one."""
        if not mesh:
            return
        try:
            world_size = int(mesh["world_size"])
            live = sorted(int(r) for r in mesh["live"])
        except (KeyError, TypeError, ValueError):
            raise ConfigError(
                f"checkpoint mesh membership is malformed: {mesh!r}"
            ) from None
        if self.rank not in live:
            raise ConfigError(
                f"checkpoint mesh membership evicted this rank "
                f"({self.rank}; live {live}) — a dead rank must not "
                f"resume into stripes the survivors re-covered")
        if not all(0 <= r < world_size for r in live):
            raise ConfigError(f"checkpoint mesh membership out of range: "
                              f"live {live} for world_size {world_size}")
        self.world_size = world_size
        self.live = live
        self.evicted = [dict(e) for e in mesh.get("evicted", [])]
        gauge("mesh_live_ranks",
              help="ranks with a fresh, non-final shard").set(self.n_live)
        self.log.record("membership_restored", live=list(self.live),
                        world_size=world_size)
        emit_event({"event": "membership_restored", "rank": self.rank,
                    "live": list(self.live), "world_size": world_size})

    # -- reporting ---------------------------------------------------------

    def summary(self) -> dict:
        return {"world_size": self.world_size, "rank": self.rank,
                "live": list(self.live),
                "evicted": [dict(e) for e in self.evicted],
                "shrunk": bool(self.evicted)}

    def dump_causal(self, path, meta: dict | None = None):
        """Writes this rank's causal log (membership transitions + mined
        blocks) as a forensics-readable dump. Deterministic: records
        carry no wall clock, so same-seed ``mesh.rank_death`` runs are
        byte-identical (the elastic-smoke gate asserts this)."""
        return dump_causal_logs(
            [self.log], path,
            meta={"world_size": self.world_size, "rank": self.rank,
                  **(meta or {})})


class ElasticMiner(Miner):
    """A Miner whose per-candidate sweep covers only this rank's stripe
    of the nonce space, re-striped by its ElasticWorld on every
    eviction. The chain it mines is valid (full PoW + linkage through
    the C++ Node) but rank-dependent — the world's aggregate sweep per
    template covers the whole space exactly once, which is the
    throughput contract striping exists for."""

    def __init__(self, config, world: ElasticWorld, backend=None,
                 log_fn=None):
        super().__init__(config, node_id=world.rank, backend=backend,
                         log_fn=log_fn)
        self.world = world

    def search_windows(self):
        return self.world.stripe_windows(self.config.batch_size)

    def _begin_block(self, height: int) -> None:
        # One supervision step (fault site + staleness oracle + any
        # resulting re-stripe) before every block's first consumed sweep
        # — the base drivers' per-block hook, so BOTH the sequential
        # oracle and the pipelined driver supervise identically. In the
        # pipelined driver a re-stripe here invalidates the in-flight
        # speculative dispatch (its windows were the dead world's), and
        # the driver discards + re-dispatches on the shrunken stripes —
        # a dead dispatch's slices are never merged into a re-mined
        # height.
        self.world.step(height)

    def _block_mined(self, rec) -> None:
        # Causal record per block: deterministic fields only (height,
        # nonce, hash prefix) — the dump-determinism contract.
        self.world.log.record("mine", step=rec.height, height=rec.height,
                              nonce=rec.nonce, hash=rec.hash[:16])


# ---- the in-process device-mesh flavor -------------------------------------


class ElasticMeshBackend:
    """MinerBackend wrapper that makes an in-process device mesh
    survivable: every sharded dispatch (the program whose epilogue is
    the psum/pmin ``winner_select``) runs under ``guarded_collective``;
    on ``RankLossSuspected`` the mesh is rebuilt one device smaller
    under the ``mesh.rebuild`` retry budget and the search retries.

    One process writes ONE meshwatch shard, so there is no per-device
    staleness asymmetry to consult here — the watchdog (or the injected
    ``parallel.collective`` fault) IS the detector, and the shrink is
    one device per suspicion, floored at a single device (past that the
    suspicion re-raises: a 1-device mesh with a dead device is a dead
    run, and rc 2 beats a silent wedge). Shrinking never changes the
    mined chain: every rung sweeps ascending rounds and takes the
    lowest qualifying nonce, so the result is n_miners-invariant
    (BASELINE.md "Tip reproducibility") — the elastic rebuild is
    byte-transparent to the determinism contract.
    """

    def __init__(self, config, mesh=None, timeout_s: float | None = None):
        if config.backend != "tpu" or config.n_miners < 2:
            raise ConfigError(
                f"ElasticMeshBackend needs a multi-device tpu config "
                f"(backend {config.backend!r}, n_miners "
                f"{config.n_miners})")
        self._config = config
        self._timeout_s = timeout_s
        self.n_live = config.n_miners
        self.evictions: list[dict] = []
        self._backend = guarded_collective(
            lambda: self._rendezvous(self.n_live, mesh),
            site="mesh.build", timeout_s=timeout_s)
        # Not mesh_live_ranks: that gauge counts RANK PROCESSES (the
        # shard-oracle world), and this flavor counts devices inside one
        # process — a combined run would make one number mean two things.
        gauge("mesh_live_devices",
              help="devices in the elastic in-process mesh").set(
            self.n_live)

    def _rendezvous(self, n_live: int, mesh=None):
        """Mesh build + sharded searcher construction — a rendezvous
        (every device must participate), so callers reach it ONLY
        through guarded_collective (chainlint SPMD004)."""
        from ..backend import get_backend
        from ..parallel.mesh import make_miner_mesh

        if mesh is None:
            mesh = make_miner_mesh(n_live)
        return get_backend("tpu",
                           batch_pow2=self._config.effective_batch_pow2,
                           n_miners=n_live, kernel=self._config.kernel,
                           mesh=mesh)

    @property
    def name(self) -> str:
        return self._backend.name

    def search(self, header80: bytes, difficulty_bits: int,
               start_nonce: int = 0, max_count: int = 1 << 32):
        while True:
            try:
                return guarded_collective(
                    lambda: self._backend.search(
                        header80, difficulty_bits,
                        start_nonce=start_nonce, max_count=max_count),
                    site="winner_select", timeout_s=self._timeout_s)
            except RankLossSuspected as e:
                self._shrink(e)

    def _shrink(self, cause: RankLossSuspected) -> None:
        """Evicts one device and rebuilds the mesh over the survivors
        under the ``mesh.rebuild`` budget; re-raises the suspicion when
        already down to one device."""
        if self.n_live <= 1:
            raise cause
        old = self.n_live
        self.n_live -= 1
        # The rebuild is itself a guarded rendezvous; transient rebuild
        # failures retry under policy_for("mesh.rebuild")
        # (MPIBT_MESH_REBUILD_RETRIES), then surface as RetryExhausted
        # (CLI rc 2).
        self._backend = call_with_retry(
            lambda: guarded_collective(
                lambda: self._rendezvous(self.n_live),
                site="mesh.rebuild", timeout_s=self._timeout_s),
            site="mesh.rebuild")
        record = {"event": "mesh_shrunk", "from": old, "to": self.n_live,
                  "reason": "suspected", "cause": str(cause)}
        self.evictions.append(record)
        counter("mesh_evicted_ranks_total",
                help="ranks evicted from the elastic mesh, by reason",
                reason="suspected").inc()
        gauge("mesh_live_devices",
              help="devices in the elastic in-process mesh").set(
            self.n_live)
        emit_event(record)

    def summary(self) -> dict:
        return {"n_miners": self._config.n_miners, "n_live": self.n_live,
                "evictions": [dict(e) for e in self.evictions],
                "shrunk": bool(self.evictions)}
