"""Measured-cost roofline cross-check: HLO cost analysis vs OPBUDGET.

The PR 14 ALU-floor proof is a *closed-form static census*
(``perfwatch.attribution.kernel_op_model`` — committed as
``alu_ops_per_nonce`` in OPBUDGET.json and ratcheted by chainlint).
This module is its independent, *measured* verification: AOT-compile
the actual multi-round sweep executable, ask XLA's own HLO cost
analysis what it costs (flops / bytes accessed), and report
flops-per-nonce next to the committed census with their ratio.

The two numbers answer different questions and are NOT expected to be
equal: the census counts u32 ALU ops the kernel *algorithm* demands
per nonce; XLA's flop count is what the *compiled program* executes
per element after CSE/fusion/strength reduction on the target backend
(rotations folded, the multi-round loop body counted once). The ratio
is the point — a kernel change that moves it sharply moved real work,
whichever ledger it hid from. ``perfwatch compiles`` surfaces it;
``make compile-smoke`` pins that the measurement stays available.

Unlike the observer (``dispatchwatch.__init__`` — cold-backend, never
imports jax), this is a CLI/bench seam in the ``experiments/roofline``
tradition: calling it imports jax deliberately, so only CLIs and
smokes call it, never the telemetry path.
"""
from __future__ import annotations

#: Default probe shape: one Pallas-tile-sized batch (2^13 nonces) at a
#: mid difficulty — big enough that per-element work dominates the
#: program, small enough to compile in ~a second on a cpu world.
PROBE_BATCH_POW2 = 13
PROBE_DIFFICULTY = 16


def measured_cost(batch_pow2: int = PROBE_BATCH_POW2,
                  difficulty_bits: int = PROBE_DIFFICULTY,
                  kernel: str = "auto") -> dict:
    """AOT-compiles the multi-round sweep (the same builder the tpu
    backend caches — ``make_multiround_search_fn``) and returns XLA's
    HLO cost analysis of the executable, normalized per nonce.

    Raises RuntimeError when jax or the cost analysis is unavailable
    (callers decide whether that fails a gate or degrades a report).
    """
    try:
        import numpy as np

        from .. import core
        from ..backend.tpu import make_multiround_search_fn
        from ..ops.sha256_sched import extend_midstate
    except ImportError as e:                        # pragma: no cover
        raise RuntimeError(f"measured cost needs jax: {e}") from e

    from . import compile_scope

    batch = 1 << batch_pow2
    fn, effective = make_multiround_search_fn(batch, difficulty_bits,
                                              kernel=kernel)
    midstate, tail = core.header_midstate(b"\x00" * 80)
    ext = extend_midstate(midstate, tail)
    with compile_scope(site="cost-probe"):
        compiled = fn.lower(ext, np.uint32(0), np.uint32(1)).compile()
    try:
        analysis = compiled.cost_analysis()
    except (AttributeError, NotImplementedError, RuntimeError) as e:
        raise RuntimeError(f"cost_analysis unavailable: {e}") from e
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    flops = float(analysis.get("flops", 0.0) or 0.0)
    bytes_accessed = float(analysis.get("bytes accessed", 0.0) or 0.0)
    return {
        "kernel": effective,
        "batch_pow2": batch_pow2,
        "difficulty_bits": difficulty_bits,
        "hlo_flops": flops,
        "hlo_bytes_accessed": bytes_accessed,
        "flops_per_nonce": round(flops / batch, 3),
        "bytes_per_nonce": round(bytes_accessed / batch, 3),
    }


def cost_cross_check(batch_pow2: int = PROBE_BATCH_POW2,
                     difficulty_bits: int = PROBE_DIFFICULTY,
                     kernel: str = "auto", root=None) -> dict:
    """``measured_cost`` joined with the committed OPBUDGET census:
    adds ``alu_ops_per_nonce`` (the PR 14 closed form, 5996 at the
    committed cut) and ``measured_over_committed`` (the ratio the
    smoke pins as present and positive). The census keys are simply
    absent when OPBUDGET.json is unreadable — measurement beats
    emptiness, the report never lies about what it compared."""
    from ..perfwatch.attribution import committed_census

    out = measured_cost(batch_pow2=batch_pow2,
                        difficulty_bits=difficulty_bits, kernel=kernel)
    budget = committed_census(root) or committed_census()
    ops = (budget or {}).get("alu_ops_per_nonce")
    if isinstance(ops, int) and ops > 0:
        out["alu_ops_per_nonce"] = ops
        out["measured_over_committed"] = round(
            out["flops_per_nonce"] / ops, 4)
    return out
