"""dispatchwatch: XLA compile / trace-cache observability.

Every other lens watches the *execution* of device programs; this one
watches their *creation*. Two surfaces, one discipline:

* **compile observer** — a ``jax.monitoring`` duration-event listener
  (``ensure_listener``) registered lazily the first time a dispatch
  seam arms a ``compile_scope``. jax has no selective unregister, so
  the listener stays registered for the life of the process and gates
  internally: under ``MPIBT_TELEMETRY_OFF`` it is a flag check and
  nothing else, and — the meshprof/memory.py cold-backend contract —
  this module NEVER imports jax: if ``jax`` is not already in
  ``sys.modules`` every probe is a zero-cost no-op and every snapshot
  is ``{}``. Backend-compile events land as ``jax_compiles_total{site}``
  + ``jax_compile_ms{site}`` in the live registry and in a bounded
  event ring the meshwatch shard writer carries a tail of.
* **trace-cache census** — the dispatch seams that cache jitted sweep
  callables (``TpuBackend._searchers`` via ``select_kernel`` /
  ``make_round_search``, ``FusedMiner._fns``, the mesh sweep) report
  their cache size through ``note_cache`` and wrap their dispatch call
  sites in ``compile_scope`` so every compile is attributed to the
  seam that paid it. Both emits carry a keyword-only ``site=`` —
  chainlint TEL007 enforces the label at every emit point, because a
  compile without one cannot be joined to its cache (the same stance
  as TEL005's skew-span site).

The per-site invariant a healthy steady-state run keeps is
``compiles == cache_entries``: every compile bought a cache entry that
is reused forever after. ``recompiles()`` prices the violation
(compiles past the cache size), the ``recompile_storm`` chainwatch
rule watches census *growth* after warmup, and ``compile_snapshot()``
is the carriage projection (shard ``compiles`` key, ``/healthz``
``compiles`` key via ``meshwatch.aggregate.mesh_compiles``, incident
bundles, the Perfetto ``xla compiles`` lane) — ``{}`` while off or
unobserved, the skew_spans/memory/incidents carriage model.

Standard library only; ``make compile-smoke`` pins the contract
(docs/observability.md §dispatchwatch).
"""
from __future__ import annotations

import sys
import threading
from collections import deque

from ..telemetry.registry import telemetry_disabled

#: jax.monitoring duration events worth watching, by program-creation
#: stage. Only ``backend_compile`` counts toward the census/storm
#: signal (an XLA executable was built); trace/lowering durations ride
#: along as per-site stage counts.
COMPILE_EVENTS = {
    "/jax/core/compile/backend_compile_duration": "backend_compile",
    "/jax/core/compile/jaxpr_trace_duration": "jaxpr_trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lowering",
}

#: Site label for compiles observed outside any ``compile_scope`` — a
#: compile nobody attributed is itself a finding worth surfacing.
UNSCOPED_SITE = "unscoped"

#: Bounded compile-event ring (same order as the skew-span ring).
COMPILE_RING_SIZE = 1024
#: Newest compile events carried per shard write / Perfetto lane.
COMPILE_TAIL_N = 64

_lock = threading.Lock()
_listening = False          # jax.monitoring listener registered (once)
_sites: dict[str, dict] = {}
_events: deque = deque(maxlen=COMPILE_RING_SIZE)
_tls = threading.local()    # per-thread compile_scope site stack


def _new_site() -> dict:
    return {"compiles": 0, "compile_ms": 0.0, "cache_entries": 0,
            "stages": {}}


def current_site() -> str:
    """The innermost live ``compile_scope`` site on this thread (the
    listener's attribution key), ``UNSCOPED_SITE`` outside any scope."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else UNSCOPED_SITE


def ensure_listener() -> bool:
    """Register the ``jax.monitoring`` duration listener, lazily and at
    most once per process. Never the reason a process imports jax: the
    gate is ``sys.modules`` membership (the meshprof/memory.py
    discipline — this can run on the shard-flusher thread while the
    main thread is mid-``import jax``, so attribute reads only, no
    imports). False while jax is absent; callers simply retry on the
    next emit."""
    global _listening
    if _listening:
        return True
    if telemetry_disabled():
        return False
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    register = getattr(getattr(jax, "monitoring", None),
                       "register_event_duration_secs_listener", None)
    if register is None:
        return False
    with _lock:
        if _listening:
            return True
        try:
            register(_on_duration)
        except Exception:
            return False
        _listening = True
    return True


def _on_duration(event: str, duration_secs: float, **kwargs) -> None:
    """The registered listener: maps jax's compile-stage duration
    events onto the per-site census. jax has no unregister, so the
    kill switch is checked here, per event — the off half of the
    overhead audit pays exactly this flag check."""
    if telemetry_disabled():
        return
    stage = COMPILE_EVENTS.get(event)
    if stage is None:
        return
    record_compile(site=current_site(), stage=stage,
                   duration_s=float(duration_secs))


def record_compile(*, site: str, stage: str = "backend_compile",
                   duration_s: float = 0.0) -> None:
    """One observed program-creation stage at ``site`` (keyword-only —
    chainlint TEL007). ``backend_compile`` stages advance the census,
    the ring, ``jax_compiles_total{site}`` and ``jax_compile_ms{site}``;
    other stages only bump the per-site stage counts."""
    if telemetry_disabled():
        return
    from ..meshprof.spans import wall_now

    site = str(site)
    ms = duration_s * 1000.0
    with _lock:
        st = _sites.setdefault(site, _new_site())
        st["stages"][stage] = st["stages"].get(stage, 0) + 1
        if stage == "backend_compile":
            st["compiles"] += 1
            st["compile_ms"] += ms
            _events.append({"t": wall_now(), "site": site,
                            "ms": round(ms, 3), "stage": stage})
    if stage == "backend_compile":
        from ..telemetry import counter, histogram

        counter("jax_compiles_total",
                help="XLA backend compiles observed, by dispatch seam",
                site=site).inc()
        histogram("jax_compile_ms",
                  help="XLA backend compile wall time per program",
                  site=site).observe(ms)


class compile_scope:
    """``with compile_scope(site="backend.tpu"): <jit call>`` — the ONE
    compile-attribution idiom (chainlint TEL007: the ``site=`` keyword
    is mandatory, and keyword-only here so the runtime agrees with the
    lint). Arms the lazy listener and stamps the site every compile
    event on this thread lands under while the scope is live. Records
    nothing under ``MPIBT_TELEMETRY_OFF``."""

    __slots__ = ("site", "_armed")

    def __init__(self, *, site: str):
        self.site = str(site)
        self._armed = not telemetry_disabled()

    def __enter__(self):
        if not self._armed:
            return self
        ensure_listener()
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.site)
        return self

    def __exit__(self, exc_type, exc, tb):
        if not self._armed:
            return False
        stack = getattr(_tls, "stack", None)
        if stack:
            stack.pop()
        return False


def note_cache(*, site: str, entries: int) -> None:
    """Per-site trace-cache census emit (``site=`` keyword-only —
    chainlint TEL007): the dispatch seams call this when their
    compiled-fn cache changes size, so the census can price
    ``compiles - cache_entries`` (the recompile signal) per seam.
    Flag-check no-op under ``MPIBT_TELEMETRY_OFF``."""
    if telemetry_disabled():
        return
    ensure_listener()
    n = int(entries)
    with _lock:
        _sites.setdefault(str(site), _new_site())["cache_entries"] = n
    from ..telemetry import gauge

    gauge("trace_cache_entries",
          help="cached compiled sweep callables, by dispatch seam",
          site=site).set(n)


def compile_census() -> dict:
    """{site: {compiles, compile_ms, cache_entries, stages}} copies,
    sorted by site — the recompile-storm rule's sample and the bundle
    overlay. ``{}`` under the kill switch or when nothing was ever
    observed (cold-backend processes stay empty-handed forever)."""
    if telemetry_disabled():
        return {}
    with _lock:
        return {site: {**st, "compile_ms": round(st["compile_ms"], 3),
                       "stages": dict(st["stages"])}
                for site, st in sorted(_sites.items())}


def compile_events_tail(n: int = COMPILE_TAIL_N) -> list[dict]:
    """Copies of the newest ``n`` compile events (the Perfetto lane's
    slices; copies because the flusher json-serializes concurrently)."""
    if telemetry_disabled():
        return []
    with _lock:
        recs = list(_events)[-n:] if n is not None else list(_events)
    return [dict(r) for r in recs]


def recompiles(census: dict | None = None) -> int:
    """Compiles the census cannot account for with a cache entry,
    summed over sites — 0 on a healthy steady-state run (each sweep
    callable compiled exactly once into its seam cache). Sites that
    never reported a cache (``unscoped``) price every compile past the
    first as a recompile."""
    if census is None:
        census = compile_census()
    total = 0
    for st in census.values():
        have = int(st.get("cache_entries", 0)) or 1
        total += max(0, int(st.get("compiles", 0)) - have)
    return total


def compile_snapshot() -> dict:
    """The carriage projection (shard ``compiles`` key, ``/healthz``
    via ``mesh_compiles``, incident bundles): per-site census + the
    newest compile events. ``{}`` while disarmed/off/unobserved — the
    skew_spans/memory/incidents carriage model, so a cold-backend rank
    costs its shard nothing."""
    if telemetry_disabled():
        return {}
    sites = compile_census()
    events = compile_events_tail()
    if not sites and not events:
        return {}
    return {"sites": sites, "events": events}


def clear_compiles() -> None:
    """Reset the census and the event ring (test / smoke-leg isolation;
    the listener registration — a process-lifetime fact — stays)."""
    with _lock:
        _sites.clear()
        _events.clear()
