"""CLI: python -m mpi_blockchain_tpu.dispatchwatch {census,smoke}

``census`` prints this process's compile census + the measured-cost
cross-check as JSON (a debugging convenience — a fresh CLI process has
an empty census until ``--probe`` compiles the probe sweep).

``smoke`` is the ``make compile-smoke`` gate (docs/observability.md
§dispatchwatch):

1. a fixed-seed instrumented cpu-world mine through the DEVICE backend
   (sequential leg, then the async pipelined leg) must compile each
   sweep callable exactly once — per-site ``compiles == cache_entries``
   and zero recompiles after warmup, judged through the perfwatch
   detector's ``compile_cache`` absolute bound (<= 0);
2. chainwatch rides both legs armed: the clean mine must fire zero
   ``recompile_storm`` incidents (the false-positive contract);
3. both legs must mine byte-identical chains (instrumentation is an
   observer, never a participant);
4. the HLO measured-cost cross-check must report a positive
   flops-per-nonce next to the committed OPBUDGET census and their
   ratio (the acceptance row ``perfwatch compiles`` serves users).
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys

#: The fixed smoke config: difficulty low enough that every block's
#: deterministic winner sits within a few 2^12 rounds (the while_loop
#: sweeps them inside ONE dispatch), batch small enough that the cpu
#: XLA compile is ~a second. Winner nonces are a pure function of
#: (prefix, difficulty), so the census cannot drift per machine.
SMOKE_DIFFICULTY = 12
SMOKE_BLOCKS = 4
SMOKE_BATCH_POW2 = 12
SMOKE_PREFIX = "dispatch"


def _mine_leg(pipeline: bool) -> dict:
    """One fixed-seed device-backend mine with a fresh census and an
    armed chainwatch; returns the leg's census + incident verdicts."""
    from .. import chainwatch
    from ..config import MinerConfig
    from ..models.miner import Miner
    from . import clear_compiles, compile_census, recompiles

    clear_compiles()
    chainwatch.install()
    try:
        cfg = MinerConfig(difficulty_bits=SMOKE_DIFFICULTY,
                          n_blocks=SMOKE_BLOCKS, backend="tpu",
                          batch_pow2=SMOKE_BATCH_POW2,
                          data_prefix=SMOKE_PREFIX)
        miner = Miner(cfg, pipeline=pipeline, log_fn=lambda rec: None)
        miner.mine_chain()
        chainwatch.evaluate(source="compile-smoke", force=True)
        storms = [i for i in chainwatch.open_incidents()
                  if i.get("rule") == "recompile_storm"]
        census = compile_census()
        return {
            "census": census,
            "recompiles": recompiles(census),
            "storm_incidents": len(storms),
            "chain": miner.chain_hashes(),
        }
    finally:
        chainwatch.uninstall()


def measure_compile_census() -> dict:
    """The ``compile_cache`` bench payload: both legs' censuses, the
    section headline ``recompiles_after_warmup`` (pipelined leg,
    bounded at 0 by detector.SECTION_BOUNDS), the determinism contract
    and the measured-cost cross-check."""
    from .cost import cost_cross_check

    seq = _mine_leg(False)
    pip = _mine_leg(True)
    try:
        cost = cost_cross_check(batch_pow2=SMOKE_BATCH_POW2,
                                difficulty_bits=SMOKE_DIFFICULTY)
    except RuntimeError as e:
        cost = {"error": str(e)}
    return {
        "backend": "tpu",
        "difficulty_bits": SMOKE_DIFFICULTY,
        "n_blocks": SMOKE_BLOCKS,
        "batch_pow2": SMOKE_BATCH_POW2,
        # The section headline, bounded by SECTION_BOUNDS (<= 0).
        "recompiles_after_warmup": pip["recompiles"],
        "recompiles_sequential": seq["recompiles"],
        "sites": pip["census"],
        "sites_sequential": seq["census"],
        "storm_incidents": seq["storm_incidents"] + pip["storm_incidents"],
        "chain_identical": seq["chain"] == pip["chain"],
        "cost": cost,
    }


def _census_clean(census: dict) -> bool:
    """Exactly-once contract for one leg: the device seam compiled, and
    every site that reported a cache holds compiles == cache_entries."""
    if "backend.tpu" not in census:
        return False
    return all(st["compiles"] == st["cache_entries"]
               for st in census.values() if st.get("cache_entries"))


def cmd_smoke(args) -> int:
    """See module docstring — the make compile-smoke gate."""
    import logging

    from ..perfwatch.detector import check_candidate
    from ..perfwatch.history import DEFAULT_HISTORY_NAME, HistoryStore

    logging.getLogger("mpi_blockchain_tpu").setLevel(logging.WARNING)
    repo_root = pathlib.Path(__file__).resolve().parent.parent.parent
    store = HistoryStore(repo_root / DEFAULT_HISTORY_NAME)
    payload = measure_compile_census()
    finding = check_candidate(store, "compile_cache", payload)
    # None of this is weather: a recompile, a storm incident, a chain
    # divergence or a missing cross-check is a real defect — one dirty
    # read fails the gate outright, no best-of-N.
    if finding.verdict == "regression":
        print(f"compile-smoke: recompiles over budget: "
              f"{finding.render()}", file=sys.stderr)
        return 1
    for leg, census in (("sequential", payload["sites_sequential"]),
                        ("pipelined", payload["sites"])):
        if not _census_clean(census):
            print(f"compile-smoke: {leg} census not exactly-once: "
                  f"{json.dumps(census, sort_keys=True)}",
                  file=sys.stderr)
            return 1
    if payload["storm_incidents"]:
        print(f"compile-smoke: clean mine fired "
              f"{payload['storm_incidents']} recompile_storm "
              f"incident(s)", file=sys.stderr)
        return 1
    if not payload["chain_identical"]:
        print("compile-smoke: pipelined chain diverged from the "
              "sequential leg", file=sys.stderr)
        return 1
    cost = payload["cost"]
    if cost.get("flops_per_nonce", 0) <= 0 or \
            "measured_over_committed" not in cost:
        print(f"compile-smoke: measured-cost cross-check incomplete: "
              f"{json.dumps(cost, sort_keys=True)}", file=sys.stderr)
        return 1
    print(json.dumps({
        "event": "compile_smoke", "ok": True,
        "recompiles_after_warmup": payload["recompiles_after_warmup"],
        "compiles": {site: st["compiles"]
                     for site, st in payload["sites"].items()},
        "storm_incidents": payload["storm_incidents"],
        "chain_identical": payload["chain_identical"],
        "flops_per_nonce": cost["flops_per_nonce"],
        "alu_ops_per_nonce": cost.get("alu_ops_per_nonce"),
        "measured_over_committed": cost.get("measured_over_committed"),
        "verdict": finding.verdict,
    }, sort_keys=True))
    return 0


def cmd_census(args) -> int:
    from . import compile_snapshot

    out = {"event": "dispatchwatch_census",
           "compiles": compile_snapshot()}
    if args.probe:
        from .cost import cost_cross_check
        try:
            out["cost"] = cost_cross_check()
        except RuntimeError as e:
            out["cost"] = {"error": str(e)}
    print(json.dumps(out, sort_keys=True))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mpi_blockchain_tpu.dispatchwatch",
        description="XLA compile/trace-cache observability")
    sub = parser.add_subparsers(dest="command", required=True)

    p_cen = sub.add_parser("census", help="this process's compile "
                                          "census as JSON")
    p_cen.add_argument("--probe", action="store_true",
                       help="also AOT-compile the probe sweep and "
                            "report the measured-cost cross-check")
    p_cen.set_defaults(fn=cmd_census)

    p_smk = sub.add_parser("smoke", help="the make compile-smoke gate: "
                                         "fixed-seed mine -> "
                                         "deterministic compile census")
    p_smk.set_defaults(fn=cmd_smoke)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
