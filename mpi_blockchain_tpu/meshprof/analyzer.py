"""Mesh-wide skew analyzer: join per-rank spans into arrival deltas.

``analyze_skew(shards)`` is a PURE function of a meshwatch shard set —
re-running it on the same shards is byte-identical (the ``skew-smoke``
determinism contract) — that aligns every rank's spans on the
(site, round) key and derives, per site:

* **clock-offset normalization** — per-rank offset = the median, over
  the rounds that rank joined, of (its arrival − the round's median
  arrival). Subtracting it first means a rank whose monotonic anchor
  (or host clock) sits a constant Δ away contributes ZERO fabricated
  skew: only round-to-round arrival VARIATION survives, which is the
  quantity that actually idles chips. The estimated offsets are
  reported (``clock_offset_ms``) so a real constant straggler — which
  is indistinguishable from a clock offset without a synchronized
  clock — is still visible, just not silently priced as skew;
* **arrival-delta distribution** — per-round skew = last normalized
  arrival − first, summarized as mean/p50/p95/max (``skew_ms``) and
  kept per round (``round_skews_ms``, round order) for the registry
  histogram;
* **the straggler** — the rank with the largest mean lag behind the
  round's first arrival (ties break to the LOWEST rank, so the verdict
  is deterministic), its lag, and the implied idle chip-time: the sum
  over rounds of every early rank's wait for the last arrival — the
  wall the mesh pays for the straggler.

``publish_skew`` mirrors a report onto the live registry
(``collective_skew_ms{site}`` histogram, ``mesh_straggler_rank``
gauge); ``skew_shape`` strips the timing values so two same-seed runs
can be compared structurally (timings are weather, the joined shape is
not); ``skew_summary`` is the bounded digest ``/healthz`` carries.
"""
from __future__ import annotations

#: Rounds need at least this many ranks to say anything about skew.
MIN_RANKS = 2


def _median(sorted_xs: list[float]) -> float:
    n = len(sorted_xs)
    mid = n // 2
    if n % 2:
        return sorted_xs[mid]
    return (sorted_xs[mid - 1] + sorted_xs[mid]) / 2.0


def _quantile(sorted_xs: list[float], q: float) -> float:
    """Nearest-rank quantile of an ascending list (deterministic, no
    interpolation surprises across Python versions)."""
    if not sorted_xs:
        return 0.0
    idx = min(int(q * len(sorted_xs)), len(sorted_xs) - 1)
    return sorted_xs[idx]


def collect_spans(shards: list[dict]) -> dict:
    """{site: {round: {rank: t_enter}}} from a shard set. Malformed
    spans are skipped (a reader must survive a half-written mesh
    directory, same tolerance as ``aggregate.read_shards``)."""
    per_site: dict[str, dict[int, dict[int, float]]] = {}
    for shard in shards:
        try:
            rank = int(shard["rank"])
        except (KeyError, TypeError, ValueError):
            continue
        for rec in shard.get("skew_spans") or []:
            if not isinstance(rec, dict):
                continue
            site = rec.get("site")
            rnd = rec.get("round")
            t = rec.get("t_enter")
            if not isinstance(site, str) or rnd is None or t is None:
                continue
            try:
                per_site.setdefault(site, {}) \
                    .setdefault(int(rnd), {})[rank] = float(t)
            except (TypeError, ValueError):
                continue
    return per_site


def analyze_skew(shards: list[dict], min_ranks: int = MIN_RANKS) -> dict:
    """The mesh-wide skew report of a shard set (see module docstring).
    Deterministic: pure function, sorted iteration, rounded floats."""
    per_site = collect_spans(shards)
    sites: dict[str, dict] = {}
    world: set[int] = set()
    overall = (-1.0, -1)        # (mean lag ms, -rank) of the straggler
    max_skew = 0.0
    for site in sorted(per_site):
        rounds = {r: a for r, a in per_site[site].items()
                  if len(a) >= min_ranks}
        if not rounds:
            continue
        ranks = sorted({rk for a in rounds.values() for rk in a})
        world.update(ranks)
        centers = {r: _median(sorted(a.values()))
                   for r, a in rounds.items()}
        offsets: dict[int, float] = {}
        for rk in ranks:
            diffs = sorted(a[rk] - centers[r]
                           for r, a in rounds.items() if rk in a)
            offsets[rk] = _median(diffs)
        skews: list[float] = []        # round order
        lag_sum = {rk: 0.0 for rk in ranks}
        lag_n = {rk: 0 for rk in ranks}
        idle_ms = 0.0
        for r in sorted(rounds):
            arrivals = rounds[r]
            norm = {rk: t - offsets[rk] for rk, t in arrivals.items()}
            first = min(norm.values())
            last = max(norm.values())
            skews.append((last - first) * 1e3)
            for rk, t in norm.items():
                lag_sum[rk] += (t - first) * 1e3
                lag_n[rk] += 1
                idle_ms += (last - t) * 1e3
        mean_lag = {rk: lag_sum[rk] / lag_n[rk] for rk in ranks}
        straggler = max(ranks, key=lambda rk: (mean_lag[rk], -rk))
        asc = sorted(skews)
        dist = {"mean": round(sum(skews) / len(skews), 3),
                "p50": round(_quantile(asc, 0.50), 3),
                "p95": round(_quantile(asc, 0.95), 3),
                "max": round(asc[-1], 3)}
        max_skew = max(max_skew, asc[-1])
        if (mean_lag[straggler], -straggler) > overall:
            overall = (mean_lag[straggler], -straggler)
        sites[site] = {
            "rounds": len(rounds),
            "ranks": ranks,
            "clock_offset_ms": {str(rk): round(offsets[rk] * 1e3, 3)
                                for rk in ranks},
            "skew_ms": dist,
            "round_skews_ms": [round(s, 3) for s in skews],
            "per_rank_lag_ms": {str(rk): round(mean_lag[rk], 3)
                                for rk in ranks},
            "straggler_rank": straggler,
            "straggler_lag_ms": round(mean_lag[straggler], 3),
            "idle_chip_ms": round(idle_ms, 3),
        }
    return {
        "version": 1,
        "world": sorted(world),
        "site_count": len(sites),
        "sites": sites,
        "straggler_rank": -overall[1] if sites else -1,
        "max_skew_ms": round(max_skew, 3),
    }


def skew_shape(report: dict) -> dict:
    """The structural projection of a report — what joined, not how
    long it took. Two same-seed runs must produce identical shapes
    (the skew-smoke cross-run determinism gate): timings are weather,
    the (site, round, rank) join is not."""
    return {
        "world": list(report.get("world", [])),
        "sites": {site: {"rounds": v["rounds"], "ranks": list(v["ranks"])}
                  for site, v in sorted(report.get("sites", {}).items())},
    }


def skew_summary(report: dict) -> dict:
    """The bounded digest the mesh ``/healthz`` payload carries: enough
    to name the straggler and size the problem without shipping every
    round's delta on every scrape."""
    return {
        "site_count": report.get("site_count", 0),
        "straggler_rank": report.get("straggler_rank", -1),
        "max_skew_ms": report.get("max_skew_ms", 0.0),
        "sites": {site: {"rounds": v["rounds"],
                         "straggler_rank": v["straggler_rank"],
                         "straggler_lag_ms": v["straggler_lag_ms"],
                         "skew_p95_ms": v["skew_ms"]["p95"],
                         "idle_chip_ms": v["idle_chip_ms"]}
                  for site, v in sorted(report.get("sites", {}).items())},
    }


def publish_skew(report: dict) -> None:
    """Mirror a report onto the live registry: one
    ``collective_skew_ms`` observation per joined round under its
    ``site`` label, and the mesh-wide straggler gauge (per-site under
    ``site``, overall unlabeled). No-op under the kill switch (the
    telemetry helpers hand back NULL_METRIC then)."""
    from ..telemetry import gauge, histogram

    for site, v in sorted(report.get("sites", {}).items()):
        h = histogram("collective_skew_ms",
                      help="per-round rendezvous arrival skew (last "
                           "arrival - first, clock-offset normalized)",
                      site=site)
        for s in v["round_skews_ms"]:
            h.observe(s)
        gauge("mesh_straggler_rank",
              help="rank with the largest mean rendezvous lag "
                   "(-1: no joined rounds)",
              site=site).set(v["straggler_rank"])
    gauge("mesh_straggler_rank",
          help="rank with the largest mean rendezvous lag "
               "(-1: no joined rounds)").set(
        report.get("straggler_rank", -1))
