"""meshprof: collective-skew, straggler & device-memory observability.

The lens ROADMAP item 1 (the live v5e-8 scale-out) needs on day one:
the quantity that governs multi-chip efficiency is per-rendezvous
ARRIVAL SKEW at the collective boundary (the one-psum-one-pmin
``winner_select`` contract pinned in SHARDBUDGET.json) — the chips that
arrive early idle until the straggler shows up, and nothing else in the
stack measured that wait. Three pieces (docs/observability.md
§meshprof):

* **spans** — every rank stamps monotonic enter/exit times per
  collective site (``skew_span(site=...)``, the TEL005-linted emit
  idiom) into a bounded ring its meshwatch shard carries. Instrumented
  seams: ``resilience.elastic.guarded_collective`` (every guarded
  rendezvous, by its real site label), the ``parallel.mesh`` sharded
  sweep dispatch, and the elastic world's per-block lockstep
  supervision step — the rendezvous-equivalent a process-per-rank cpu
  world joins on.
* **analyzer** — joins per-rank shards into per-(site, round)
  arrival-delta distributions, names the straggler rank, its lag, and
  the implied idle chip-time; per-rank clock offsets are normalized
  out first so differing monotonic bases cannot fabricate skew.
  ``publish_skew`` mirrors a report onto the live registry
  (``collective_skew_ms{site}`` histogram + ``mesh_straggler_rank``
  gauge).
* **memory** — per-device HBM/byte watermarks sampled at dispatch
  boundaries (``jax`` ``memory_stats()`` where available, a zero-cost
  no-op elsewhere: jax is never imported by this package), surfaced in
  the shards, ``/healthz``, and the perfwatch ``memory`` axis.

Standard library only — importing this package never pulls in jax
(the telemetry-package contract), and every emit point is a strict
no-op under ``MPIBT_TELEMETRY_OFF`` (the blocktrace overhead self-audit
prices the live emit points; the off leg must cost nothing).
"""
from __future__ import annotations

from .analyzer import (analyze_skew, publish_skew,  # noqa: F401
                       skew_shape, skew_summary)
from .memory import (device_memory_stats, memory_snapshot,  # noqa: F401
                     sample_memory)
from .spans import clear_spans, skew_span, spans_tail  # noqa: F401
