"""Per-device memory telemetry: HBM/byte watermarks at dispatch edges.

``sample_memory()`` is called from the pipeline profiler's dispatch
boundary — the one per-sweep host touchpoint the overhead self-audit
already prices — and throttled to at most one real sample per
``SAMPLE_INTERVAL_S`` so a hot mining loop pays a clock read, not a
device query. ``device_memory_stats()`` reads ``jax``'s per-device
``memory_stats()`` where a backend provides it (TPU does; cpu devices
usually return None) and — the hard contract — NEVER imports jax: if
``jax`` is not already in ``sys.modules`` the whole module is a
zero-cost no-op, so the resilience/telemetry packages stay importable
on a bare coordinator host.

Watermarks: for each device the peak observed ``bytes_in_use`` (and
``peak_bytes_in_use`` where the allocator reports it) is kept across
samples, because the interesting OOM precursor is the high-water mark
between scrapes, not the instantaneous value the scrape happens to see.
``memory_snapshot()`` is the shard/healthz projection and force-samples
first so a freshly started rank is never empty-handed.
"""
from __future__ import annotations

import sys
import threading
import time

from ..telemetry.registry import telemetry_disabled

#: Minimum seconds between real device queries from the hot path.
SAMPLE_INTERVAL_S = 0.5

#: memory_stats() keys worth carrying when present (allocator-dependent).
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_alloc_size", "num_allocs")

_lock = threading.Lock()
_last_sample = 0.0
_watermarks: dict[str, dict] = {}


def device_memory_stats() -> dict:
    """{device: memory_stats subset} for every jax device that reports
    stats. Empty dict when jax was never imported (the gate is
    ``sys.modules`` membership — this module must not be the reason a
    process loads jax), when no backend has been initialized yet, or
    when no backend provides ``memory_stats``."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {}
    # Only READ devices from an already-initialized backend:
    # jax.devices() on a cold process would initialize one, which both
    # costs seconds and — fatally — breaks a later
    # jax.distributed.initialize() (the multiprocess mesh launch arms
    # the shard flusher, hence this sampler, BEFORE joining the world).
    # The probe must not IMPORT anything either: this runs on the
    # flusher thread, and importing jax._src.xla_bridge while the main
    # thread is mid-`import jax` leaves the bridge module partially
    # initialized under jax's own feet (per-module import locks don't
    # serialize the two entry points). sys.modules lookups only.
    xla_bridge = sys.modules.get("jax._src.xla_bridge")
    if not getattr(xla_bridge, "_backends", None):
        return {}
    try:
        devices = jax.devices()
    except (AttributeError, RuntimeError, ValueError):
        return {}
    out: dict[str, dict] = {}
    for d in devices:
        try:
            stats = d.memory_stats()
        except (AttributeError, RuntimeError, TypeError,
                ValueError, NotImplementedError):
            continue
        if not stats:
            continue
        picked = {k: int(stats[k]) for k in _STAT_KEYS
                  if isinstance(stats.get(k), (int, float))}
        if picked:
            out[str(d)] = picked
    return out


def sample_memory(*, force: bool = False) -> dict:
    """Throttled watermark update from the dispatch hot path. Returns
    the current watermark map (shared reference is never exposed —
    callers get the module view via ``memory_snapshot``)."""
    global _last_sample
    if telemetry_disabled():
        return {}
    now = time.monotonic()
    with _lock:
        if not force and now - _last_sample < SAMPLE_INTERVAL_S:
            return _watermarks
        _last_sample = now
    stats = device_memory_stats()
    if not stats:
        return _watermarks
    with _lock:
        for dev, cur in stats.items():
            mark = _watermarks.setdefault(dev, {})
            for k, v in cur.items():
                if k in ("bytes_in_use", "peak_bytes_in_use",
                         "largest_alloc_size"):
                    mark[k] = max(mark.get(k, 0), v)
                else:
                    mark[k] = v
            mark["last_bytes_in_use"] = cur.get("bytes_in_use",
                                                mark.get("last_bytes_in_use", 0))
    return _watermarks


def memory_snapshot() -> dict:
    """Copy of the per-device watermarks for the shard writer /
    ``/healthz`` (force-samples so a new rank reports on first flush).
    Empty dict where jax is absent — the schema key is always present,
    its value just stays ``{}`` off-accelerator."""
    if telemetry_disabled():
        return {}
    sample_memory(force=True)
    with _lock:
        return {dev: dict(mark) for dev, mark in sorted(_watermarks.items())}


def clear_memory() -> None:
    """Reset watermarks and the throttle (test isolation)."""
    global _last_sample
    with _lock:
        _watermarks.clear()
        _last_sample = 0.0
