"""Rendezvous skew spans: per-site monotonic enter/exit stamps.

Every rank wraps its collective-boundary waits in ``skew_span(site=...)``
(``site`` is keyword-only — chainlint TEL005 enforces the label at every
emit site, because a span without one cannot be joined across ranks).
Each span records:

* ``site``  — the collective site label (``winner_select``,
  ``mesh.build``, ``mesh.rebuild``, ``mesh.sweep``, ``block.step``);
* ``round`` — a per-site monotonically increasing local index, assigned
  at ENTER. Every rank passes the same sites in the same order (the
  SPMD lockstep contract SPMD001-004 protect), so (site, round) is the
  cross-rank join key the analyzer aligns arrivals on;
* ``t_enter`` / ``t_exit`` — wall-anchored monotonic floats (one anchor
  per process, the ``meshwatch.pipeline`` convention): monotonic within
  a process, wall-comparable across same-host ranks. Cross-process
  anchors still differ by a small constant; the analyzer estimates and
  subtracts that per-rank offset, so a clock base can never read as
  skew (docs/observability.md §meshprof);
* ``height`` / ``template`` — stamped from the in-scope
  ``blocktrace.trace_block`` frame, so skew joins to blocks;
* ``ok`` — False when the wait raised (a timed-out rendezvous is
  exactly the overhang worth seeing).

Spans land in a bounded process-global ring the meshwatch shard writer
carries a tail of (``skew_spans``). Standard library only; strict no-op
under ``MPIBT_TELEMETRY_OFF`` (the overhead-audit contract).
"""
from __future__ import annotations

import threading
import time
from collections import deque

from ..telemetry.registry import telemetry_disabled

#: Ring capacity — same order as the pipeline profiler's record ring.
SKEW_RING_SIZE = 4096
#: Newest spans carried per meshwatch shard write.
SKEW_TAIL_N = 256

# One anchor per process: time.time() sampled once against perf_counter,
# so stamps are monotonic (perf_counter) yet wall-scaled (the same
# convention as PipelineProfiler._anchor — the two timelines must lay
# on one Perfetto axis).
_ANCHOR = time.time() - time.perf_counter()


def wall_now() -> float:
    """Wall-anchored monotonic now — the span timestamp base."""
    return _ANCHOR + time.perf_counter()


_lock = threading.Lock()
_ring: deque = deque(maxlen=SKEW_RING_SIZE)
_rounds: dict[str, int] = {}


class skew_span:
    """``with skew_span(site="winner_select"): <rendezvous wait>`` —
    the ONE skew-span emit idiom (chainlint TEL005: the ``site=``
    keyword is mandatory, and keyword-only here so the runtime agrees
    with the lint). Records nothing under ``MPIBT_TELEMETRY_OFF``."""

    __slots__ = ("site", "_round", "_t0", "_armed")

    def __init__(self, *, site: str):
        self.site = str(site)
        self._armed = not telemetry_disabled()
        self._round = 0
        self._t0 = 0.0

    def __enter__(self):
        if not self._armed:
            return self
        # Round index assigned at ENTER: two ranks inside the same
        # rendezvous agree on the round even if their exits interleave.
        with _lock:
            n = _rounds.get(self.site, 0)
            _rounds[self.site] = n + 1
        self._round = n
        self._t0 = wall_now()
        return self

    def __exit__(self, exc_type, exc, tb):
        if not self._armed:
            return False
        t1 = wall_now()
        rec = {"site": self.site, "round": self._round,
               "t_enter": self._t0, "t_exit": t1,
               "ok": exc_type is None}
        # Late import: blocktrace.context is stdlib-only but importing
        # it at module load would make the spans module heavier than
        # the resilience package (which must stay jax-free AND lean).
        from ..blocktrace.context import current_trace

        trace = current_trace()
        if trace is not None:
            rec["height"] = trace.height
            if trace.template:
                rec["template"] = trace.template
        with _lock:
            _ring.append(rec)
        return False


def spans_tail(n: int = SKEW_TAIL_N) -> list[dict]:
    """Copies of the newest ``n`` spans (the shard writer's carriage;
    copies because the flusher json-serializes concurrently)."""
    with _lock:
        recs = list(_ring)[-n:] if n is not None else list(_ring)
    return [dict(r) for r in recs]


def clear_spans() -> None:
    """Empty the ring and reset every site's round counter (test/CLI
    isolation — a fresh measurement must join rounds from zero)."""
    with _lock:
        _ring.clear()
        _rounds.clear()
