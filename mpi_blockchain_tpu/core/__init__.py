"""Python bindings for the C++ chain core.

The C++ ``Block``/``Chain``/``Node`` classes are the canonical chain state
(BASELINE.json north-star); this module is a thin, typed veneer. Headers
cross the boundary as 80-byte serialized blobs, hashes as 32-byte digests.

Two interchangeable binding layers over the same libchaincore sources:

* **pybind11** (``src/pybind_module.cpp``) — the mechanism the north-star
  names. Header-only pybind11 is vendored in this image inside the torch /
  tensorflow include trees, so the extension builds offline.
* **ctypes** over the C ABI (``src/capi.cpp``) — the fallback when no
  pybind11 headers exist.

``MBT_BINDING={auto,pybind11,ctypes}`` forces the choice (auto prefers
pybind11); ``core.BINDING`` records what actually loaded. Both expose the
exact same surface, and the backend-equivalence suite runs against either.
"""
from __future__ import annotations

import dataclasses
import os
import struct

import numpy as np

HEADER_SIZE = 80
NOT_FOUND = 2**64 - 1

_CHOICE = os.environ.get("MBT_BINDING", "auto")
if _CHOICE not in ("auto", "pybind11", "ctypes"):
    raise ValueError(f"MBT_BINDING must be auto|pybind11|ctypes, "
                     f"got {_CHOICE!r}")

_pb = None
BINDING_FALLBACK_REASON: str | None = None
if _CHOICE in ("auto", "pybind11"):
    try:
        from .build import ensure_pybind_built
        _pb = ensure_pybind_built()
    except Exception as e:
        if _CHOICE == "pybind11":
            raise
        # auto mode falls back to ctypes, but never silently: a pybind
        # build regression outside CI must stay visible (ADVICE round 2).
        import warnings
        BINDING_FALLBACK_REASON = f"{type(e).__name__}: {e}"
        warnings.warn(
            "chaincore pybind11 binding unavailable "
            f"({BINDING_FALLBACK_REASON}); falling back to the ctypes "
            "binding. Set MBT_BINDING=pybind11 to make this fatal.",
            RuntimeWarning, stacklevel=2)

if _pb is not None:
    BINDING = "pybind11"
    sha256 = _pb.sha256
    sha256d = _pb.sha256d
    header_hash = _pb.header_hash
    leading_zero_bits = _pb.leading_zero_bits
    cpu_search = _pb.cpu_search
    Node = _pb.Node

    def header_midstate(header80: bytes) -> tuple[np.ndarray, np.ndarray]:
        """Midstate after chunk 1 + the 16 chunk-2 words (nonce word 3).

        Returns uint32 arrays (8,) and (16,) shared bit-for-bit with the
        TPU backend's sweep kernel.
        """
        state, tail = _pb.header_midstate(header80)
        return (np.frombuffer(state, np.uint32).copy(),
                np.frombuffer(tail, np.uint32).copy())
else:
    BINDING = "ctypes"
    from ._ctypes_binding import (Node, cpu_search,          # noqa: F401
                                  header_hash, header_midstate,
                                  leading_zero_bits, sha256, sha256d)


@dataclasses.dataclass(frozen=True)
class HeaderFields:
    """Decoded view of the frozen 80-byte header layout (chain.hpp)."""
    version: int
    prev_hash: bytes
    data_hash: bytes
    timestamp: int
    bits: int
    nonce: int

    @classmethod
    def unpack(cls, header80: bytes) -> "HeaderFields":
        v, = struct.unpack_from("<I", header80, 0)
        t, b, n = struct.unpack_from("<III", header80, 68)
        return cls(v, header80[4:36], header80[36:68], t, b, n)

    def pack(self) -> bytes:
        return (struct.pack("<I", self.version) + self.prev_hash +
                self.data_hash + struct.pack("<III", self.timestamp,
                                             self.bits, self.nonce))


def set_nonce(header80: bytes, nonce: int) -> bytes:
    """Returns the header with its nonce field (bytes 76..80, LE) replaced."""
    return header80[:76] + struct.pack("<I", nonce)


def make_candidate_header(prev_hash: bytes, data: bytes, height: int,
                          bits: int) -> bytes:
    """Python twin of ``Node::make_candidate`` (chain.cpp) for a KNOWN
    prev digest: the pipelined miner builds block ``height``'s candidate
    from sweep N's winning digest *before* the C++ append lands, which
    is what lets sweep N+1 dispatch while the host validates/appends N.
    Field-for-field identical to the C++ builder: version = kVersion
    (1), deterministic timestamp == height, nonce = 0. The driver
    re-checks equality against ``node.make_candidate`` at every block
    boundary and discards the speculation on any mismatch (e.g. a
    retarget schedule changing ``bits``), so drift here degrades to a
    discarded dispatch, never a divergent chain."""
    return HeaderFields(version=1, prev_hash=prev_hash,
                        data_hash=sha256d(data), timestamp=int(height),
                        bits=int(bits), nonce=0).pack()


class RecvResult:
    """Mirror of chaincore::RecvResult."""
    APPENDED = 0
    DUPLICATE = 1
    STALE_OR_FORK = 2
    INVALID = 3
    REORGED = 4
    IGNORED_SHORTER = 5
