"""ctypes binding over the libchaincore C ABI (capi.cpp) — the fallback
Python<->C++ boundary when pybind11 headers are unavailable (the spec'd
pybind11 extension is preferred; see core/__init__.py binding selection).

Headers cross the boundary as 80-byte serialized blobs, hashes as 32-byte
digests. Exports exactly the same surface as the pybind11 module.
"""
from __future__ import annotations

import ctypes

import numpy as np

from .build import ensure_built

HEADER_SIZE = 80
NOT_FOUND = 2**64 - 1

_lib = ctypes.CDLL(str(ensure_built()))

_u8p = ctypes.POINTER(ctypes.c_uint8)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)

_lib.cc_sha256.argtypes = [ctypes.c_char_p, ctypes.c_uint64, _u8p]
_lib.cc_sha256d.argtypes = [ctypes.c_char_p, ctypes.c_uint64, _u8p]
_lib.cc_header_hash.argtypes = [ctypes.c_char_p, _u8p]
_lib.cc_leading_zero_bits.argtypes = [ctypes.c_char_p]
_lib.cc_leading_zero_bits.restype = ctypes.c_int
_lib.cc_header_midstate.argtypes = [ctypes.c_char_p, _u32p, _u32p]
_lib.cc_search.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
                           ctypes.c_uint32, _u64p]
_lib.cc_search.restype = ctypes.c_uint64

_lib.cc_node_new.argtypes = [ctypes.c_uint32, ctypes.c_int]
_lib.cc_node_new.restype = ctypes.c_void_p
_lib.cc_node_free.argtypes = [ctypes.c_void_p]
_lib.cc_node_height.argtypes = [ctypes.c_void_p]
_lib.cc_node_height.restype = ctypes.c_uint64
_lib.cc_node_difficulty.argtypes = [ctypes.c_void_p]
_lib.cc_node_difficulty.restype = ctypes.c_uint32
_lib.cc_node_tip_hash.argtypes = [ctypes.c_void_p, _u8p]
_lib.cc_node_block_hash.argtypes = [ctypes.c_void_p, ctypes.c_uint64, _u8p]
_lib.cc_node_block_header.argtypes = [ctypes.c_void_p, ctypes.c_uint64, _u8p]
_lib.cc_node_make_candidate.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_uint64, _u8p]
_lib.cc_node_submit.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
_lib.cc_node_submit.restype = ctypes.c_int
_lib.cc_node_receive.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
_lib.cc_node_receive.restype = ctypes.c_int
_lib.cc_node_adopt_chain.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_uint64]
_lib.cc_node_adopt_chain.restype = ctypes.c_int
_lib.cc_node_adopt_suffix.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                      ctypes.c_char_p, ctypes.c_uint64]
_lib.cc_node_adopt_suffix.restype = ctypes.c_int
_lib.cc_node_find.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
_lib.cc_node_find.restype = ctypes.c_int64
_lib.cc_node_headers_from.argtypes = [ctypes.c_void_p, ctypes.c_uint64, _u8p]
_lib.cc_node_headers_from.restype = ctypes.c_uint64
_lib.cc_node_save.argtypes = [ctypes.c_void_p, _u8p]
_lib.cc_node_save.restype = ctypes.c_uint64
_lib.cc_node_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint64]
_lib.cc_node_load.restype = ctypes.c_int
_lib.cc_node_rollback.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
_lib.cc_node_set_retarget.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                      ctypes.c_uint32, ctypes.c_uint32]
_lib.cc_node_set_retarget.restype = ctypes.c_int
_lib.cc_node_next_bits.argtypes = [ctypes.c_void_p]
_lib.cc_node_next_bits.restype = ctypes.c_uint32


def _out_buf(n: int):
    return (ctypes.c_uint8 * n)()


def sha256(data: bytes) -> bytes:
    out = _out_buf(32)
    _lib.cc_sha256(data, len(data), out)
    return bytes(out)


def sha256d(data: bytes) -> bytes:
    out = _out_buf(32)
    _lib.cc_sha256d(data, len(data), out)
    return bytes(out)


def header_hash(header80: bytes) -> bytes:
    assert len(header80) == HEADER_SIZE
    out = _out_buf(32)
    _lib.cc_header_hash(header80, out)
    return bytes(out)


def leading_zero_bits(digest32: bytes) -> int:
    assert len(digest32) == 32
    return _lib.cc_leading_zero_bits(digest32)


def header_midstate(header80: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Midstate after chunk 1 and the 16 chunk-2 words (nonce word index 3).

    Returns uint32 arrays (8,) and (16,) shared bit-for-bit with the TPU
    backend's sweep kernel.
    """
    assert len(header80) == HEADER_SIZE
    state = (ctypes.c_uint32 * 8)()
    tail = (ctypes.c_uint32 * 16)()
    _lib.cc_header_midstate(header80, state, tail)
    return (np.frombuffer(bytes(state), np.uint32).copy(),
            np.frombuffer(bytes(tail), np.uint32).copy())


def cpu_search(header80: bytes, start_nonce: int, count: int,
               difficulty_bits: int) -> tuple[int | None, int]:
    """Sequential lowest-nonce search. Returns (nonce or None, hashes_tried)."""
    tried = ctypes.c_uint64(0)
    n = _lib.cc_search(header80, start_nonce, count, difficulty_bits,
                       ctypes.byref(tried))
    return (None if n == NOT_FOUND else n), tried.value


class Node:
    """Handle to a C++ chaincore::Node — the canonical chain state."""

    def __init__(self, difficulty_bits: int, node_id: int = 0):
        self._h = _lib.cc_node_new(difficulty_bits, node_id)
        self.node_id = node_id

    def __del__(self):
        h = getattr(self, "_h", None)
        lib = globals().get("_lib")
        if h and lib is not None:
            lib.cc_node_free(h)
            self._h = None

    @property
    def height(self) -> int:
        return _lib.cc_node_height(self._h)

    @property
    def difficulty_bits(self) -> int:
        return _lib.cc_node_difficulty(self._h)

    @property
    def tip_hash(self) -> bytes:
        out = _out_buf(32)
        _lib.cc_node_tip_hash(self._h, out)
        return bytes(out)

    def block_hash(self, height: int) -> bytes:
        if not 0 <= height <= self.height:
            raise IndexError(f"height {height} not in [0, {self.height}]")
        out = _out_buf(32)
        _lib.cc_node_block_hash(self._h, height, out)
        return bytes(out)

    def block_header(self, height: int) -> bytes:
        if not 0 <= height <= self.height:
            raise IndexError(f"height {height} not in [0, {self.height}]")
        out = _out_buf(HEADER_SIZE)
        _lib.cc_node_block_header(self._h, height, out)
        return bytes(out)

    def make_candidate(self, data: bytes) -> bytes:
        out = _out_buf(HEADER_SIZE)
        _lib.cc_node_make_candidate(self._h, data, len(data), out)
        return bytes(out)

    def submit(self, header80: bytes) -> bool:
        return bool(_lib.cc_node_submit(self._h, header80))

    def receive(self, header80: bytes) -> int:
        return _lib.cc_node_receive(self._h, header80)

    def adopt_chain(self, headers80: list[bytes]) -> int:
        blob = b"".join(headers80)
        return _lib.cc_node_adopt_chain(self._h, blob, len(headers80))

    def adopt_suffix(self, anchor: int, headers80: list[bytes]) -> int:
        """Suffix adoption above a common ancestor (O(suffix) sync)."""
        blob = b"".join(headers80)
        return _lib.cc_node_adopt_suffix(self._h, anchor, blob,
                                         len(headers80))

    def find(self, digest32: bytes) -> int:
        """Height of this block hash on the chain, or -1 (O(1))."""
        if len(digest32) != 32:    # ValueError like the pybind11 binding;
            # an assert would vanish under -O and pass a short buffer to C
            raise ValueError("digest must be 32 bytes")
        return _lib.cc_node_find(self._h, digest32)

    def headers_from(self, from_height: int) -> list[bytes]:
        """Headers for heights from_height+1..tip (suffix-sync wire
        format; headers_from(0) == all_headers())."""
        n = max(self.height - from_height, 0)
        out = _out_buf(n * HEADER_SIZE)
        got = _lib.cc_node_headers_from(self._h, from_height, out)
        blob = bytes(out)
        return [blob[i * HEADER_SIZE:(i + 1) * HEADER_SIZE]
                for i in range(got)]

    def save(self) -> bytes:
        out = _out_buf((self.height + 1) * HEADER_SIZE)
        n = _lib.cc_node_save(self._h, out)
        return bytes(out)[: n * HEADER_SIZE]

    def load(self, blob: bytes) -> bool:
        if not blob or len(blob) % HEADER_SIZE != 0:
            return False
        return bool(_lib.cc_node_load(self._h, blob, len(blob) // HEADER_SIZE))

    def rollback(self, new_height: int) -> None:
        _lib.cc_node_rollback(self._h, new_height)

    def set_retarget(self, interval: int, step: int = 1,
                     max_bits: int = 0) -> bool:
        """Arms the height-scheduled difficulty-retarget rule (interval 0
        disables). False once blocks beyond genesis exist — the rule is
        frozen with history."""
        return bool(_lib.cc_node_set_retarget(self._h, interval, step,
                                              max_bits))

    def next_bits(self) -> int:
        """Bits the NEXT block (height+1) must carry under the rule."""
        return _lib.cc_node_next_bits(self._h)

    def all_headers(self) -> list[bytes]:
        """Headers for heights 1..tip (the adopt_chain wire format)."""
        return self.headers_from(0)
