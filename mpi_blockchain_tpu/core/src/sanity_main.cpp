// Sanitizer driver (SURVEY.md §5 "race detection"): exercises the exact
// threading pattern the framework uses in production — N threads running the
// nonce search concurrently over disjoint ranges on a shared read-only
// header (backend/cpu.py releases the GIL around cc_search) — plus the
// single-threaded chain append / fork / longest-chain reorg state machine.
// Built with -fsanitize=thread or -fsanitize=address (make tsan / asan)
// and run by tests/test_sanitizers.py. Exits 0 iff all checks pass; the
// sanitizers abort non-zero on a race / memory error.
#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include "chain.hpp"
#include "sha256.hpp"

using namespace chaincore;

namespace {

// Mirrors cc_search (capi.cpp): lowest qualifying nonce in
// [start, start+count), or UINT64_MAX.
uint64_t search_range(const BlockHeader& header, uint64_t start,
                      uint64_t count) {
  BlockHeader h = header;
  uint8_t digest[32];
  uint64_t end = start + count;
  for (uint64_t n = start; n < end; ++n) {
    h.nonce = static_cast<uint32_t>(n);
    h.hash(digest);
    if (leading_zero_bits(digest) >= static_cast<int>(h.bits)) return n;
  }
  return UINT64_MAX;
}

}  // namespace

int main() {
  constexpr uint32_t kDifficulty = 12;
  constexpr int kThreads = 4;
  constexpr uint64_t kSlice = 1 << 12;

  Node node(kDifficulty, 0);

  // Mine 4 blocks, each via a kThreads-way parallel search on a shared
  // candidate header (the production threading pattern).
  for (int blk = 0; blk < 4; ++blk) {
    char payload[32];
    std::snprintf(payload, sizeof payload, "block:%d", blk + 1);
    const BlockHeader cand = node.make_candidate(
        reinterpret_cast<const uint8_t*>(payload), std::strlen(payload));

    std::atomic<uint64_t> best{UINT64_MAX};
    for (uint64_t base = 0; best.load() == UINT64_MAX; base += kThreads * kSlice) {
      std::vector<std::thread> threads;
      std::vector<uint64_t> found(kThreads, UINT64_MAX);
      for (int t = 0; t < kThreads; ++t) {
        threads.emplace_back([&, t] {
          found[t] = search_range(cand, base + t * kSlice, kSlice);
        });
      }
      for (auto& th : threads) th.join();
      // Lowest-nonce winner rule: first round with any qualifier yields the
      // global minimum (every smaller nonce already swept).
      for (int t = 0; t < kThreads; ++t) {
        if (found[t] != UINT64_MAX) {
          uint64_t cur = best.load();
          if (found[t] < cur) best.store(found[t]);
        }
      }
      if (base > (1ull << 32)) {
        std::fprintf(stderr, "no nonce found\n");
        return 1;
      }
    }
    BlockHeader won = cand;
    won.nonce = static_cast<uint32_t>(best.load());
    if (!node.submit(won)) {
      std::fprintf(stderr, "submit failed at block %d\n", blk + 1);
      return 1;
    }
  }
  if (node.height() != 4) return 1;

  // Fork + longest-chain reorg on a second node (single-threaded state
  // machine, still under the sanitizer for memory errors).
  Node other(kDifficulty, 1);
  for (int blk = 0; blk < 5; ++blk) {
    char payload[32];
    std::snprintf(payload, sizeof payload, "fork:%d", blk + 1);
    BlockHeader cand = other.make_candidate(
        reinterpret_cast<const uint8_t*>(payload), std::strlen(payload));
    uint64_t nonce = 0;
    for (uint64_t base = 0;; base += kSlice) {
      nonce = search_range(cand, base, kSlice);
      if (nonce != UINT64_MAX) break;
    }
    cand.nonce = static_cast<uint32_t>(nonce);
    if (!other.submit(cand)) return 1;
  }
  std::vector<BlockHeader> longer;
  for (uint64_t h = 1; h <= other.height(); ++h)
    longer.push_back(other.chain().at(h).header);
  if (node.adopt_chain(longer) != RecvResult::kReorged) {
    std::fprintf(stderr, "reorg not adopted\n");
    return 1;
  }
  if (node.height() != 5) return 1;
  uint8_t a[32], b[32];
  node.chain().tip().header.hash(a);
  other.chain().tip().header.hash(b);
  if (std::memcmp(a, b, 32) != 0) return 1;

  std::puts("sanity ok");
  return 0;
}
