#include "chain.hpp"

#include "sha256.hpp"

namespace chaincore {

namespace {
inline void store_le32(uint8_t* p, uint32_t v) {
  p[0] = uint8_t(v);
  p[1] = uint8_t(v >> 8);
  p[2] = uint8_t(v >> 16);
  p[3] = uint8_t(v >> 24);
}
inline uint32_t load_le32(const uint8_t* p) {
  return uint32_t(p[0]) | (uint32_t(p[1]) << 8) | (uint32_t(p[2]) << 16) |
         (uint32_t(p[3]) << 24);
}
}  // namespace

void BlockHeader::serialize(uint8_t out[kHeaderSize]) const {
  store_le32(out, version);
  std::memcpy(out + 4, prev_hash, 32);
  std::memcpy(out + 36, data_hash, 32);
  store_le32(out + 68, timestamp);
  store_le32(out + 72, bits);
  store_le32(out + 76, nonce);
}

BlockHeader BlockHeader::deserialize(const uint8_t in[kHeaderSize]) {
  BlockHeader h;
  h.version = load_le32(in);
  std::memcpy(h.prev_hash, in + 4, 32);
  std::memcpy(h.data_hash, in + 36, 32);
  h.timestamp = load_le32(in + 68);
  h.bits = load_le32(in + 72);
  h.nonce = load_le32(in + 76);
  return h;
}

void BlockHeader::hash(uint8_t out[32]) const {
  uint8_t buf[kHeaderSize];
  serialize(buf);
  sha256d(buf, kHeaderSize, out);
}

bool BlockHeader::meets_difficulty() const {
  uint8_t h[32];
  hash(h);
  return leading_zero_bits(h) >= int(bits);
}

Block Block::from_header(const BlockHeader& h, uint64_t height) {
  Block b;
  b.header = h;
  b.height = height;
  h.hash(b.hash);
  return b;
}

namespace {
inline std::string hash_key(const uint8_t hash[32]) {
  return std::string(reinterpret_cast<const char*>(hash), 32);
}
}  // namespace

Chain::Chain(uint32_t difficulty_bits) : difficulty_bits_(difficulty_bits) {
  BlockHeader genesis;
  genesis.version = kVersion;
  // prev_hash stays all-zero.
  static const char kGenesisPayload[] = "genesis";
  sha256d(reinterpret_cast<const uint8_t*>(kGenesisPayload),
          sizeof(kGenesisPayload) - 1, genesis.data_hash);
  genesis.timestamp = 0;
  genesis.bits = difficulty_bits;
  genesis.nonce = 0;
  blocks_.push_back(Block::from_header(genesis, 0));
  index_add(blocks_.back());
}

void Chain::index_add(const Block& b) { index_[hash_key(b.hash)] = b.height; }

int64_t Chain::find(const uint8_t hash[32]) const {
  auto it = index_.find(hash_key(hash));
  return it == index_.end() ? -1 : int64_t(it->second);
}

bool Chain::set_retarget(uint32_t interval, uint32_t step,
                         uint32_t max_bits) {
  // Changing the rule once non-genesis blocks exist would retroactively
  // re-judge history under a different schedule; refuse.
  if (height() > 0) return false;
  retarget_interval_ = interval;
  retarget_step_ = step;
  retarget_max_bits_ = max_bits;
  return true;
}

uint32_t Chain::expected_bits(uint64_t height) const {
  if (retarget_interval_ == 0 || height == 0) return difficulty_bits_;
  // 64-bit accumulate: a hostile height can never overflow back under
  // the clamp.
  uint64_t bits = uint64_t(difficulty_bits_) +
                  uint64_t(retarget_step_) * (height / retarget_interval_);
  uint64_t cap = retarget_max_bits_ ? retarget_max_bits_ : 255;
  if (cap < difficulty_bits_) cap = difficulty_bits_;
  if (bits > cap) bits = cap;
  return uint32_t(bits);
}

bool Chain::valid_child(const BlockHeader& header, const Block& parent) const {
  if (header.version != kVersion) return false;
  if (std::memcmp(header.prev_hash, parent.hash, 32) != 0) return false;
  if (header.timestamp != uint32_t(parent.height + 1)) return false;
  // The retarget schedule is enforced HERE, on every adoption path —
  // append, try_adopt, and try_adopt_from all funnel through valid_child,
  // so a synced suffix is judged under the same rule as a local submit.
  if (header.bits != expected_bits(parent.height + 1)) return false;
  return header.meets_difficulty();
}

bool Chain::append(const BlockHeader& header) {
  if (!valid_child(header, tip())) return false;
  blocks_.push_back(Block::from_header(header, height() + 1));
  index_add(blocks_.back());
  return true;
}

bool Chain::try_adopt(const std::vector<BlockHeader>& headers) {
  return try_adopt_from(0, headers);
}

bool Chain::try_adopt_from(uint64_t anchor,
                           const std::vector<BlockHeader>& headers) {
  if (anchor > height()) return false;
  if (anchor + headers.size() <= height()) return false;  // not strictly longer
  // Fork point: the longest prefix of `headers` byte-identical to our own
  // blocks anchor+1..height(). Shared blocks were fully validated when
  // first adopted, so only the divergent suffix needs hashing and
  // validation — adopt cost is O(suffix), not O(height).
  uint8_t ours[kHeaderSize], theirs[kHeaderSize];
  size_t fork = 0;  // number of leading shared headers
  while (anchor + fork + 1 < blocks_.size() && fork < headers.size()) {
    blocks_[anchor + fork + 1].header.serialize(ours);
    headers[fork].serialize(theirs);
    if (std::memcmp(ours, theirs, kHeaderSize) != 0) break;
    ++fork;
  }
  const Block* parent = &blocks_[anchor + fork];
  std::vector<Block> suffix;
  suffix.reserve(headers.size() - fork);
  for (size_t i = fork; i < headers.size(); ++i) {
    if (!valid_child(headers[i], *parent)) return false;  // chain unchanged
    suffix.push_back(Block::from_header(headers[i], parent->height + 1));
    parent = &suffix.back();
  }
  rollback_to(anchor + fork);
  for (const Block& b : suffix) {
    blocks_.push_back(b);
    index_add(blocks_.back());
  }
  return true;
}

void Chain::rollback_to(uint64_t new_height) {
  while (blocks_.size() > new_height + 1) {
    index_.erase(hash_key(blocks_.back().hash));
    blocks_.pop_back();
  }
}

std::vector<uint8_t> Chain::save() const {
  std::vector<uint8_t> out(blocks_.size() * kHeaderSize);
  for (size_t i = 0; i < blocks_.size(); ++i)
    blocks_[i].header.serialize(out.data() + i * kHeaderSize);
  return out;
}

std::vector<uint8_t> Chain::headers_from(uint64_t from_height) const {
  if (from_height >= height()) return {};
  uint64_t n = height() - from_height;
  std::vector<uint8_t> out(n * kHeaderSize);
  for (uint64_t i = 0; i < n; ++i)
    blocks_[from_height + 1 + i].header.serialize(out.data() +
                                                  i * kHeaderSize);
  return out;
}

bool Chain::load(const std::vector<uint8_t>& bytes, uint32_t difficulty_bits,
                 Chain* out, uint32_t retarget_interval,
                 uint32_t retarget_step, uint32_t retarget_max_bits) {
  if (bytes.empty() || bytes.size() % kHeaderSize != 0) return false;
  Chain fresh(difficulty_bits);
  fresh.set_retarget(retarget_interval, retarget_step, retarget_max_bits);
  // Byte 0..79 must be exactly our deterministic genesis.
  uint8_t genesis_buf[kHeaderSize];
  fresh.blocks_[0].header.serialize(genesis_buf);
  if (std::memcmp(bytes.data(), genesis_buf, kHeaderSize) != 0) return false;
  size_t n = bytes.size() / kHeaderSize;
  std::vector<BlockHeader> rest;
  rest.reserve(n - 1);
  for (size_t i = 1; i < n; ++i)
    rest.push_back(BlockHeader::deserialize(bytes.data() + i * kHeaderSize));
  if (!rest.empty() && !fresh.try_adopt(rest)) return false;
  *out = std::move(fresh);
  return true;
}

BlockHeader Node::make_candidate(const uint8_t* data, size_t len) const {
  BlockHeader h;
  h.version = kVersion;
  std::memcpy(h.prev_hash, chain_.tip().hash, 32);
  sha256d(data, len, h.data_hash);
  h.timestamp = uint32_t(chain_.height() + 1);
  h.bits = chain_.expected_bits(chain_.height() + 1);
  h.nonce = 0;
  return h;
}

bool Node::submit(const BlockHeader& header) { return chain_.append(header); }

RecvResult Node::on_block_received(const BlockHeader& header) {
  uint8_t h[32];
  header.hash(h);
  // O(1) duplicate check via the chain's hash index (was an O(height)
  // scan — O(height^2) over a long simulation).
  if (chain_.find(h) >= 0) return RecvResult::kDuplicate;
  if (std::memcmp(header.prev_hash, chain_.tip().hash, 32) == 0) {
    return chain_.append(header) ? RecvResult::kAppended : RecvResult::kInvalid;
  }
  // Does not extend our tip and is not a block we have: the caller must
  // fetch the sender's chain for longest-chain resolution (SURVEY.md §3.3).
  return RecvResult::kStaleOrFork;
}

RecvResult Node::adopt_chain(const std::vector<BlockHeader>& headers) {
  if (headers.size() <= chain_.height()) return RecvResult::kIgnoredShorter;
  return chain_.try_adopt(headers) ? RecvResult::kReorged
                                   : RecvResult::kInvalid;
}

RecvResult Node::adopt_suffix(uint64_t anchor,
                              const std::vector<BlockHeader>& headers) {
  if (anchor > chain_.height()) return RecvResult::kInvalid;
  if (anchor + headers.size() <= chain_.height())
    return RecvResult::kIgnoredShorter;
  return chain_.try_adopt_from(anchor, headers) ? RecvResult::kReorged
                                                : RecvResult::kInvalid;
}

}  // namespace chaincore
