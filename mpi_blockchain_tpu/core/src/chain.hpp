// Chain core: Block header layout, Chain container, Node state machine.
//
// Rebuild of the reference's Block/Node C++ classes (SURVEY.md §1 layers 2-4,
// 6; BASELINE.json north-star: "Block/Node C++ classes stay as the canonical
// chain state"). The reference mount was empty this round, so the design is
// built to the BASELINE.json capability contract, not traced source.
//
// FROZEN 80-byte header byte layout (both the CPU and the TPU/JAX backends
// depend on this exact serialization — see SURVEY.md §7 "hard parts" #1):
//
//   offset size field       encoding
//   0      4    version     uint32 little-endian
//   4      32   prev_hash   raw digest bytes of the previous block
//   36     32   data_hash   sha256d of the block payload
//   68     4    timestamp   uint32 little-endian (deterministic: == height)
//   72     4    bits        uint32 little-endian (difficulty, leading-0 bits)
//   76     4    nonce       uint32 little-endian
//
// The nonce sits in the second SHA-256 chunk, enabling the midstate
// optimization shared by every backend. Timestamps are deterministic (equal
// to the block height) so that a chain's block hashes are a pure function of
// (genesis, payload data, difficulty) — the executable form of the
// north-star's "identical block hashes" requirement.
#pragma once
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace chaincore {

constexpr size_t kHeaderSize = 80;
constexpr uint32_t kVersion = 1;

struct BlockHeader {
  uint32_t version = kVersion;
  uint8_t prev_hash[32] = {0};
  uint8_t data_hash[32] = {0};
  uint32_t timestamp = 0;
  uint32_t bits = 0;
  uint32_t nonce = 0;

  void serialize(uint8_t out[kHeaderSize]) const;
  static BlockHeader deserialize(const uint8_t in[kHeaderSize]);
  // sha256d of the serialized header — the block hash.
  void hash(uint8_t out[32]) const;
  // Proof-of-work check: leading_zero_bits(hash) >= bits.
  bool meets_difficulty() const;
};

struct Block {
  BlockHeader header;
  uint64_t height = 0;
  uint8_t hash[32] = {0};  // cached sha256d of the header

  static Block from_header(const BlockHeader& h, uint64_t height);
};

// Append-only chain with longest-chain reorg support.
class Chain {
 public:
  // Constructs a chain holding only the fixed genesis block. Genesis is
  // deterministic given `difficulty_bits`: version=1, prev=0^32,
  // data_hash=sha256d("genesis"), timestamp=0, bits=difficulty, nonce=0.
  // Genesis is exempt from the PoW check.
  explicit Chain(uint32_t difficulty_bits);

  uint64_t height() const { return blocks_.size() - 1; }  // genesis = height 0
  const Block& tip() const { return blocks_.back(); }
  const Block& at(uint64_t h) const { return blocks_[h]; }
  uint32_t difficulty_bits() const { return difficulty_bits_; }

  // Height-scheduled difficulty retargeting (ISSUE 6). Timestamps are
  // structural (== height), so the only retarget rule every validator can
  // agree on from header bytes alone is a pure function of height:
  //
  //   expected_bits(h) = min(difficulty_bits + step * (h / interval),
  //                          max_bits)            for h >= 1
  //   expected_bits(0) = difficulty_bits          (genesis, PoW-exempt)
  //
  // interval == 0 disables retargeting (the default; expected_bits is then
  // the constant difficulty_bits — existing chains are byte-unchanged).
  // The schedule is enforced by valid_child, i.e. on EVERY adoption path
  // (append, try_adopt, try_adopt_from), not just on locally mined blocks.
  // Returns false (rule unchanged) if blocks beyond genesis already exist:
  // changing the rule mid-chain would retroactively invalidate history.
  bool set_retarget(uint32_t interval, uint32_t step, uint32_t max_bits);
  uint32_t expected_bits(uint64_t height) const;
  uint32_t retarget_interval() const { return retarget_interval_; }
  uint32_t retarget_step() const { return retarget_step_; }
  uint32_t retarget_max_bits() const { return retarget_max_bits_; }

  // Validates `header` as the next block (linkage, deterministic timestamp,
  // bits, PoW) and appends. Returns false (chain unchanged) if invalid.
  bool append(const BlockHeader& header);

  // Validation of a header as a child of `parent` under this chain's rules.
  bool valid_child(const BlockHeader& header, const Block& parent) const;

  // Longest-chain rule: `headers` is a full replacement chain, heights
  // 1..headers.size(), child of this chain's genesis. Adopts iff it is
  // fully valid and strictly longer than the current chain. Returns true
  // on adoption. Cost is O(suffix): the longest byte-identical prefix
  // shared with the current chain was already validated when first
  // adopted, so only the divergent suffix is hashed and checked.
  bool try_adopt(const std::vector<BlockHeader>& headers);

  // Suffix form of the longest-chain rule (SURVEY.md §3.3 "request chain
  // (suffix) from r"): `headers` are heights anchor+1..anchor+n, children
  // of OUR block at `anchor` (a common ancestor the sync protocol
  // established). Adopts iff fully valid and the result is strictly
  // longer. Makes fork-heal TRANSFER O(suffix), matching the O(suffix)
  // validation try_adopt already has; try_adopt == try_adopt_from(0, ...).
  bool try_adopt_from(uint64_t anchor, const std::vector<BlockHeader>& headers);

  // Drops blocks above `new_height` (reorg rollback primitive).
  void rollback_to(uint64_t new_height);

  // Height of the block with this hash, or -1 if absent. O(1) via the
  // hash index (kills the O(chain) duplicate scan in Node receive).
  int64_t find(const uint8_t hash[32]) const;

  // Serialization: concatenated 80-byte headers (heights 0..tip).
  std::vector<uint8_t> save() const;
  // Concatenated headers for heights from_height+1..tip (the suffix-sync
  // wire format; empty when from_height >= height()). The ONE serve-side
  // implementation both bindings expose.
  std::vector<uint8_t> headers_from(uint64_t from_height) const;
  // Rebuilds a chain from saved bytes; validates everything above genesis.
  // Returns false if the bytes do not form a valid chain. The optional
  // retarget triple re-arms the schedule the saved chain was mined under
  // (0/0/0 = no retargeting), so validation judges it by its own rule.
  static bool load(const std::vector<uint8_t>& bytes, uint32_t difficulty_bits,
                   Chain* out, uint32_t retarget_interval = 0,
                   uint32_t retarget_step = 0, uint32_t retarget_max_bits = 0);

 private:
  void index_add(const Block& b);

  std::vector<Block> blocks_;
  // block hash (32 raw bytes) -> height; kept in sync by every mutation.
  std::unordered_map<std::string, uint64_t> index_;
  uint32_t difficulty_bits_;
  // Retarget schedule (0/0/0 = disabled; see set_retarget above).
  uint32_t retarget_interval_ = 0;
  uint32_t retarget_step_ = 0;
  uint32_t retarget_max_bits_ = 0;
};

// Result of handing a peer's block to a Node (SURVEY.md §3.3).
enum class RecvResult : int {
  kAppended = 0,     // extended our tip; local miner must restart on new tip
  kDuplicate = 1,    // already have it
  kStaleOrFork = 2,  // does not extend our tip: caller should fetch the
                     // sender's full chain and call Node::adopt_chain
  kInvalid = 3,      // failed PoW / bits / timestamp validation
  kReorged = 4,      // (from adopt_chain) we switched to a longer chain
  kIgnoredShorter = 5
};

// One blockchain node: owns a Chain, issues mining candidates, accepts
// winning nonces, and applies the consensus rules to peers' blocks.
// The nonce *search* itself lives behind the miner_backend plugin boundary
// (Python side; BASELINE.json north-star) — the Node never searches.
class Node {
 public:
  Node(uint32_t difficulty_bits, int node_id)
      : chain_(difficulty_bits), id_(node_id) {}

  const Chain& chain() const { return chain_; }
  int id() const { return id_; }
  uint64_t height() const { return chain_.height(); }

  // Arms the chain's height-scheduled retarget rule (see Chain::
  // set_retarget); call before any block beyond genesis exists.
  bool set_retarget(uint32_t interval, uint32_t step, uint32_t max_bits) {
    return chain_.set_retarget(interval, step, max_bits);
  }
  // The bits the NEXT block (height()+1) must carry under the rule —
  // what a search backend must target.
  uint32_t next_bits() const { return chain_.expected_bits(height() + 1); }

  // Builds the candidate header for the next block: prev = tip hash,
  // data_hash = sha256d(data), timestamp = height+1, bits = difficulty,
  // nonce = 0 (to be filled by the search backend).
  BlockHeader make_candidate(const uint8_t* data, size_t len) const;

  // Submits a mined candidate (nonce filled in). Validates and appends.
  bool submit(const BlockHeader& header);

  // Consensus entry point for a block announced by a peer.
  RecvResult on_block_received(const BlockHeader& header);

  // Longest-chain adoption of a peer's full chain (heights 1..n).
  RecvResult adopt_chain(const std::vector<BlockHeader>& headers);

  // Suffix adoption above a common ancestor at `anchor` (the O(suffix)
  // sync protocol's entry point). kReorged on adoption, kIgnoredShorter
  // when not strictly longer, kInvalid otherwise.
  RecvResult adopt_suffix(uint64_t anchor,
                          const std::vector<BlockHeader>& headers);

  Chain& mutable_chain() { return chain_; }

 private:
  Chain chain_;
  int id_;
};

}  // namespace chaincore
