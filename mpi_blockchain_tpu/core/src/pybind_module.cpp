// pybind11 bindings for the C++ chain core — the Python<->C++ boundary
// named by the BASELINE.json north-star ("Block/Node C++ classes ...
// exposed via pybind11").
//
// pybind11 is header-only; this image vendors its headers inside the torch
// and tensorflow include trees, and the build (core/build.py) points -I at
// whichever is present. The CPython-agnostic C ABI (capi.cpp + ctypes)
// remains as the fallback binding when no pybind11 headers exist —
// core/__init__.py selects at import time (MBT_BINDING={auto,pybind11,
// ctypes}).
//
// The bound surface mirrors the ctypes veneer exactly: headers cross as
// 80-byte bytes blobs, hashes as 32-byte digests, and the Node object is
// the canonical chain state.
#include <pybind11/pybind11.h>
#include <pybind11/stl.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

#include "chain.hpp"
#include "sha256.hpp"

namespace py = pybind11;
using namespace chaincore;

namespace {

py::bytes to_bytes(const uint8_t* p, size_t n) {
  return py::bytes(reinterpret_cast<const char*>(p), n);
}

const uint8_t* data8(const std::string& s) {
  return reinterpret_cast<const uint8_t*>(s.data());
}

const std::string& check80(const std::string& h) {
  if (h.size() != kHeaderSize)
    throw py::value_error("header must be exactly 80 bytes");
  return h;
}

uint64_t checked_height(const Node& n, int64_t height) {
  if (height < 0 || uint64_t(height) > n.height())
    throw py::index_error("height " + std::to_string(height) +
                          " not in [0, " + std::to_string(n.height()) + "]");
  return uint64_t(height);
}

// Concatenated 80-byte headers -> list[bytes] (the suffix-sync wire format).
std::vector<py::bytes> to_header_list(const std::vector<uint8_t>& bytes) {
  std::vector<py::bytes> out;
  out.reserve(bytes.size() / kHeaderSize);
  for (size_t i = 0; i < bytes.size(); i += kHeaderSize)
    out.push_back(to_bytes(bytes.data() + i, kHeaderSize));
  return out;
}

// list[bytes] (80 each) -> parsed headers, validating lengths.
std::vector<BlockHeader> parse_headers(
    const std::vector<std::string>& headers80) {
  std::vector<BlockHeader> hs;
  hs.reserve(headers80.size());
  for (const std::string& h : headers80)
    hs.push_back(BlockHeader::deserialize(data8(check80(h))));
  return hs;
}

// Sequential lowest-nonce sweep (same contract as capi.cpp cc_search; both
// delegate to the shared chaincore::midstate_sweep). GIL released: the CPU
// miner_backend runs this from 8 "rank" threads.
std::pair<uint64_t, uint64_t> search_impl(const std::string& header80,
                                          uint64_t start_nonce,
                                          uint64_t count,
                                          uint32_t difficulty_bits) {
  uint64_t tried = 0;
  uint64_t nonce = midstate_sweep(data8(header80), start_nonce, count,
                                  difficulty_bits, &tried);
  return {nonce, tried};
}

}  // namespace

PYBIND11_MODULE(chaincore_pb, m) {
  m.doc() = "pybind11 bindings for the chaincore C++ chain kernel";
  m.attr("HEADER_SIZE") = py::int_(kHeaderSize);

  // ---------- hashing primitives ----------
  m.def("sha256", [](const py::bytes& data) {
    std::string s = data;
    uint8_t out[32];
    sha256(data8(s), s.size(), out);
    return to_bytes(out, 32);
  });
  m.def("sha256d", [](const py::bytes& data) {
    std::string s = data;
    uint8_t out[32];
    sha256d(data8(s), s.size(), out);
    return to_bytes(out, 32);
  });
  m.def("header_hash", [](const std::string& header80) {
    uint8_t out[32];
    sha256d(data8(check80(header80)), kHeaderSize, out);
    return to_bytes(out, 32);
  });
  m.def("leading_zero_bits", [](const std::string& digest32) {
    if (digest32.size() != 32)
      throw py::value_error("digest must be 32 bytes");
    return leading_zero_bits(data8(digest32));
  });
  m.def("header_midstate", [](const std::string& header80) {
    uint32_t state[8], tail[16];
    header_midstate(data8(check80(header80)), state, tail);
    return py::make_tuple(
        to_bytes(reinterpret_cast<uint8_t*>(state), sizeof state),
        to_bytes(reinterpret_cast<uint8_t*>(tail), sizeof tail));
  });

  // ---------- CPU nonce search (the cpu miner_backend) ----------
  m.def(
      "cpu_search",
      [](const std::string& header80, uint64_t start_nonce, uint64_t count,
         uint32_t difficulty_bits) {
        std::pair<uint64_t, uint64_t> r;
        {
          py::gil_scoped_release release;
          r = search_impl(header80, start_nonce, count, difficulty_bits);
        }
        return py::make_tuple(
            r.first == UINT64_MAX ? py::object(py::none())
                                  : py::object(py::int_(r.first)),
            r.second);
      },
      py::arg("header80"), py::arg("start_nonce"), py::arg("count"),
      py::arg("difficulty_bits"));

  // ---------- Node: the canonical chain state ----------
  py::class_<Node>(m, "Node")
      .def(py::init<uint32_t, int>(), py::arg("difficulty_bits"),
           py::arg("node_id") = 0)
      .def_property_readonly("height", &Node::height)
      .def_property_readonly(
          "difficulty_bits",
          [](const Node& n) { return n.chain().difficulty_bits(); })
      .def_property_readonly("node_id", &Node::id)
      .def_property_readonly("tip_hash", [](const Node& n) {
        return to_bytes(n.chain().tip().hash, 32);
      })
      .def("block_hash",
           [](const Node& n, int64_t height) {
             return to_bytes(n.chain().at(checked_height(n, height)).hash, 32);
           })
      .def("block_header",
           [](const Node& n, int64_t height) {
             uint8_t out[kHeaderSize];
             n.chain().at(checked_height(n, height)).header.serialize(out);
             return to_bytes(out, kHeaderSize);
           })
      .def("make_candidate",
           [](const Node& n, const py::bytes& data) {
             std::string s = data;
             uint8_t out[kHeaderSize];
             n.make_candidate(data8(s), s.size()).serialize(out);
             return to_bytes(out, kHeaderSize);
           })
      .def("submit",
           [](Node& n, const std::string& header80) {
             return n.submit(BlockHeader::deserialize(data8(check80(
                 header80))));
           })
      .def("receive",
           [](Node& n, const std::string& header80) {
             return int(n.on_block_received(
                 BlockHeader::deserialize(data8(check80(header80)))));
           })
      .def("adopt_chain",
           [](Node& n, const std::vector<std::string>& headers80) {
             return int(n.adopt_chain(parse_headers(headers80)));
           })
      .def("adopt_suffix",
           [](Node& n, uint64_t anchor,
              const std::vector<std::string>& headers80) {
             // Suffix adoption above a common ancestor (O(suffix) sync).
             return int(n.adopt_suffix(anchor, parse_headers(headers80)));
           })
      .def("find",
           [](const Node& n, const std::string& digest32) {
             // Height of this block hash on the chain, or -1 (O(1)).
             if (digest32.size() != 32)
               throw py::value_error("digest must be 32 bytes");
             return n.chain().find(data8(digest32));
           })
      .def("headers_from",
           [](const Node& n, uint64_t from_height) {
             // Headers for heights from_height+1..tip (the suffix-sync
             // wire format; headers_from(0) == all_headers()).
             return to_header_list(n.chain().headers_from(from_height));
           })
      .def("save",
           [](const Node& n) {
             std::vector<uint8_t> bytes = n.chain().save();
             return to_bytes(bytes.data(), bytes.size());
           })
      .def("load",
           [](Node& n, const std::string& blob) {
             if (blob.empty() || blob.size() % kHeaderSize != 0) return false;
             std::vector<uint8_t> buf(blob.begin(), blob.end());
             Chain fresh(n.chain().difficulty_bits());
             // Validate under the node's CURRENT retarget rule, so a
             // retargeted chain round-trips through save()/load().
             if (!Chain::load(buf, n.chain().difficulty_bits(), &fresh,
                              n.chain().retarget_interval(),
                              n.chain().retarget_step(),
                              n.chain().retarget_max_bits()))
               return false;
             n.mutable_chain() = std::move(fresh);
             return true;
           })
      .def("set_retarget",
           [](Node& n, uint32_t interval, uint32_t step, uint32_t max_bits) {
             // Height-scheduled difficulty retargeting (Chain::
             // set_retarget; interval 0 disables). False once blocks
             // beyond genesis exist — the rule is frozen with history.
             return n.set_retarget(interval, step, max_bits);
           },
           py::arg("interval"), py::arg("step") = 1, py::arg("max_bits") = 0)
      .def("next_bits",
           [](const Node& n) {
             // Bits the NEXT block (height+1) must carry under the rule.
             return n.next_bits();
           })
      .def("rollback",
           [](Node& n, uint64_t new_height) {
             n.mutable_chain().rollback_to(new_height);
           })
      .def("all_headers", [](const Node& n) {
        // Headers for heights 1..tip (the adopt_chain wire format) ==
        // headers_from(0), through the same shared Chain implementation.
        return to_header_list(n.chain().headers_from(0));
      });
}
