// extern "C" boundary for ctypes — the FALLBACK Python <-> C++ binding.
//
// BASELINE.json's north-star names pybind11 for this boundary, and since
// round 2 the pybind11 extension (src/pybind_module.cpp, built against the
// headers vendored in the image's torch/tensorflow include trees) is the
// default. This CPython-agnostic C ABI stays as the fallback for
// environments with no pybind11 headers (SURVEY.md §7 hard part #7). Both
// bindings expose the identical surface: the C++ Block/Node classes remain
// the canonical chain state; Python sees only opaque Node handles, 80-byte
// serialized headers, and 32-byte digests.
#include <cstdint>
#include <cstring>
#include <vector>

#include "chain.hpp"
#include "sha256.hpp"

using namespace chaincore;

extern "C" {

// ---------- hashing primitives ----------

void cc_sha256(const uint8_t* data, uint64_t len, uint8_t out[32]) {
  sha256(data, len, out);
}

void cc_sha256d(const uint8_t* data, uint64_t len, uint8_t out[32]) {
  sha256d(data, len, out);
}

void cc_header_hash(const uint8_t header80[80], uint8_t out[32]) {
  sha256d(header80, kHeaderSize, out);
}

int cc_leading_zero_bits(const uint8_t h[32]) { return leading_zero_bits(h); }

// Midstate + chunk-2 word template for an 80-byte header (see sha256.hpp).
void cc_header_midstate(const uint8_t header80[80], uint32_t out_state[8],
                        uint32_t out_tail_w[16]) {
  header_midstate(header80, out_state, out_tail_w);
}

// ---------- CPU nonce search (the cpu miner_backend) ----------

// Sequential lowest-nonce-first sweep; the shared chaincore::midstate_sweep
// implements the deterministic "lowest qualifying nonce" winner rule
// (BASELINE.json north-star requirement) for both bindings.
uint64_t cc_search(const uint8_t header80[80], uint64_t start_nonce,
                   uint64_t count, uint32_t difficulty_bits,
                   uint64_t* hashes_tried) {
  return midstate_sweep(header80, start_nonce, count, difficulty_bits,
                        hashes_tried);
}

// ---------- Node / Chain object API ----------

void* cc_node_new(uint32_t difficulty_bits, int node_id) {
  return new Node(difficulty_bits, node_id);
}

void cc_node_free(void* node) { delete static_cast<Node*>(node); }

uint64_t cc_node_height(void* node) {
  return static_cast<Node*>(node)->height();
}

uint32_t cc_node_difficulty(void* node) {
  return static_cast<Node*>(node)->chain().difficulty_bits();
}

void cc_node_tip_hash(void* node, uint8_t out[32]) {
  std::memcpy(out, static_cast<Node*>(node)->chain().tip().hash, 32);
}

void cc_node_block_hash(void* node, uint64_t height, uint8_t out[32]) {
  const Chain& c = static_cast<Node*>(node)->chain();
  if (height > c.height()) {  // defense in depth; Python raises first
    std::memset(out, 0, 32);
    return;
  }
  std::memcpy(out, c.at(height).hash, 32);
}

void cc_node_block_header(void* node, uint64_t height, uint8_t out80[80]) {
  const Chain& c = static_cast<Node*>(node)->chain();
  if (height > c.height()) {
    std::memset(out80, 0, kHeaderSize);
    return;
  }
  c.at(height).header.serialize(out80);
}

void cc_node_make_candidate(void* node, const uint8_t* data, uint64_t len,
                            uint8_t out80[80]) {
  static_cast<Node*>(node)->make_candidate(data, len).serialize(out80);
}

// Returns 1 on success (validated + appended), 0 otherwise.
int cc_node_submit(void* node, const uint8_t header80[80]) {
  return static_cast<Node*>(node)->submit(BlockHeader::deserialize(header80))
             ? 1
             : 0;
}

// Returns the RecvResult enum value.
int cc_node_receive(void* node, const uint8_t header80[80]) {
  return int(static_cast<Node*>(node)->on_block_received(
      BlockHeader::deserialize(header80)));
}

// headers = n concatenated 80-byte headers for heights 1..n.
// Returns the RecvResult enum value (kReorged on adoption).
int cc_node_adopt_chain(void* node, const uint8_t* headers, uint64_t n) {
  std::vector<BlockHeader> hs;
  hs.reserve(n);
  for (uint64_t i = 0; i < n; ++i)
    hs.push_back(BlockHeader::deserialize(headers + i * kHeaderSize));
  return int(static_cast<Node*>(node)->adopt_chain(hs));
}

// Suffix adoption above a common ancestor at `anchor` (O(suffix) sync).
// headers = n concatenated 80-byte headers for heights anchor+1..anchor+n.
// Returns the RecvResult enum value (kReorged on adoption).
int cc_node_adopt_suffix(void* node, uint64_t anchor, const uint8_t* headers,
                         uint64_t n) {
  std::vector<BlockHeader> hs;
  hs.reserve(n);
  for (uint64_t i = 0; i < n; ++i)
    hs.push_back(BlockHeader::deserialize(headers + i * kHeaderSize));
  return int(static_cast<Node*>(node)->adopt_suffix(anchor, hs));
}

// Height of the block with this hash on the node's chain, or -1 (O(1)
// via the chain's hash index) — the sync protocol's common-ancestor probe.
int64_t cc_node_find(void* node, const uint8_t hash32[32]) {
  return static_cast<Node*>(node)->chain().find(hash32);
}

// Serves the headers ABOVE from_height (heights from_height+1..tip) as
// concatenated 80-byte headers into `out` (caller allocates
// (height - from_height)*80 bytes). Returns the number of headers written;
// 0 when from_height >= height.
uint64_t cc_node_headers_from(void* node, uint64_t from_height, uint8_t* out) {
  std::vector<uint8_t> bytes =
      static_cast<Node*>(node)->chain().headers_from(from_height);
  if (!bytes.empty()) std::memcpy(out, bytes.data(), bytes.size());
  return bytes.size() / kHeaderSize;
}

// Writes the whole chain (genesis..tip) as concatenated headers into `out`
// (caller allocates (height+1)*80 bytes). Returns the number of headers.
uint64_t cc_node_save(void* node, uint8_t* out) {
  std::vector<uint8_t> bytes = static_cast<Node*>(node)->chain().save();
  std::memcpy(out, bytes.data(), bytes.size());
  return bytes.size() / kHeaderSize;
}

// Restores chain state from concatenated headers (validates everything,
// under the node's CURRENT retarget rule). Returns 1 on success.
int cc_node_load(void* node, const uint8_t* bytes, uint64_t n_headers) {
  Node* nd = static_cast<Node*>(node);
  std::vector<uint8_t> buf(bytes, bytes + n_headers * kHeaderSize);
  Chain fresh(nd->chain().difficulty_bits());
  if (!Chain::load(buf, nd->chain().difficulty_bits(), &fresh,
                   nd->chain().retarget_interval(),
                   nd->chain().retarget_step(),
                   nd->chain().retarget_max_bits()))
    return 0;
  nd->mutable_chain() = std::move(fresh);
  return 1;
}

// Arms the height-scheduled difficulty-retarget rule (Chain::set_retarget;
// interval 0 disables). Returns 1 on success, 0 when blocks beyond genesis
// already exist (the rule is frozen once history does).
int cc_node_set_retarget(void* node, uint32_t interval, uint32_t step,
                         uint32_t max_bits) {
  return static_cast<Node*>(node)->set_retarget(interval, step, max_bits)
             ? 1
             : 0;
}

// The difficulty bits the NEXT block (height+1) must carry under the
// chain's retarget rule — the search backend's target.
uint32_t cc_node_next_bits(void* node) {
  return static_cast<Node*>(node)->next_bits();
}

void cc_node_rollback(void* node, uint64_t new_height) {
  static_cast<Node*>(node)->mutable_chain().rollback_to(new_height);
}

}  // extern "C"
