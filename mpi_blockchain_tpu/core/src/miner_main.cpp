// Standalone native miner — the reference's single-binary launch form
// (SURVEY.md §1 layer 7: `mpirun -np N binary difficulty n_blocks`), built
// on the same chain core the Python framework binds. Ranks are threads
// sweeping disjoint contiguous nonce slices per round; the first round with
// any qualifier yields the exact global lowest nonce, so the mined chain is
// byte-identical to every other backend (the determinism contract):
//
//   ./chaincore_miner <difficulty_bits> <n_blocks> [n_threads] [out_file]
//
// Payloads are "block:<height>" — the Python MinerConfig default — so
// `python -m mpi_blockchain_tpu mine --difficulty D --blocks N --out f`
// and `./chaincore_miner D N T f` produce the same bytes.
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "chain.hpp"
#include "sha256.hpp"

using namespace chaincore;

namespace {

// Lowest qualifying nonce in [start, start+count), or UINT64_MAX.
uint64_t search_range(const BlockHeader& header, uint64_t start,
                      uint64_t count, std::atomic<uint64_t>* tried) {
  BlockHeader h = header;
  uint8_t digest[32];
  uint64_t end = start + count;
  uint64_t local = 0;
  for (uint64_t n = start; n < end; ++n) {
    h.nonce = static_cast<uint32_t>(n);
    h.hash(digest);
    ++local;
    if (leading_zero_bits(digest) >= static_cast<int>(h.bits)) {
      tried->fetch_add(local, std::memory_order_relaxed);
      return n;
    }
  }
  tried->fetch_add(local, std::memory_order_relaxed);
  return UINT64_MAX;
}

uint64_t mine_block(const BlockHeader& cand, int n_threads, uint64_t slice,
                    std::atomic<uint64_t>* tried) {
  constexpr uint64_t kNonceEnd = 1ull << 32;
  for (uint64_t base = 0; base < kNonceEnd; base += n_threads * slice) {
    std::vector<uint64_t> found(n_threads, UINT64_MAX);
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; ++t) {
      // Clamp the final round to the 2^32 nonce-space edge: an unclamped
      // range would wrap through the uint32 cast and re-test round-0
      // nonces.
      uint64_t start = base + t * slice;
      uint64_t count = start >= kNonceEnd
                           ? 0
                           : std::min(slice, kNonceEnd - start);
      threads.emplace_back([&, t, start, count] {
        found[t] = search_range(cand, start, count, tried);
      });
    }
    for (auto& th : threads) th.join();
    uint64_t best = UINT64_MAX;
    for (uint64_t f : found)
      if (f < best) best = f;
    if (best != UINT64_MAX) return best;
  }
  return UINT64_MAX;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr,
                 "usage: %s <difficulty_bits> <n_blocks> [n_threads] "
                 "[out_file]\n", argv[0]);
    return 2;
  }
  const uint32_t difficulty = std::strtoul(argv[1], nullptr, 10);
  const uint64_t n_blocks = std::strtoull(argv[2], nullptr, 10);
  const int n_threads = argc > 3 ? std::atoi(argv[3]) : 1;
  const char* out_file = argc > 4 ? argv[4] : nullptr;
  if (difficulty > 64 || n_threads < 1) {
    std::fprintf(stderr, "bad arguments\n");
    return 2;
  }

  Node node(difficulty, 0);
  std::atomic<uint64_t> tried{0};
  const uint64_t slice = 1ull << 16;

  for (uint64_t b = 1; b <= n_blocks; ++b) {
    char payload[32];
    int len = std::snprintf(payload, sizeof payload, "block:%llu",
                            static_cast<unsigned long long>(b));
    BlockHeader cand = node.make_candidate(
        reinterpret_cast<const uint8_t*>(payload), len);
    uint64_t nonce = mine_block(cand, n_threads, slice, &tried);
    if (nonce == UINT64_MAX) {
      std::fprintf(stderr, "nonce space exhausted at height %llu\n",
                   static_cast<unsigned long long>(b));
      return 1;
    }
    cand.nonce = static_cast<uint32_t>(nonce);
    if (!node.submit(cand)) {
      std::fprintf(stderr, "submit failed at height %llu\n",
                   static_cast<unsigned long long>(b));
      return 1;
    }
  }

  uint8_t tip[32];
  node.chain().tip().header.hash(tip);
  char hex[65];
  for (int i = 0; i < 32; ++i) std::snprintf(hex + 2 * i, 3, "%02x", tip[i]);
  std::printf("{\"event\": \"chain_mined\", \"backend\": \"cpp-binary\", "
              "\"height\": %llu, \"tip_hash\": \"%s\", "
              "\"hashes_tried\": %llu, \"n_threads\": %d}\n",
              static_cast<unsigned long long>(node.height()), hex,
              static_cast<unsigned long long>(tried.load()), n_threads);

  if (out_file) {
    std::vector<uint8_t> bytes = node.chain().save();
    std::FILE* f = std::fopen(out_file, "wb");
    if (!f || std::fwrite(bytes.data(), 1, bytes.size(), f) != bytes.size()) {
      std::fprintf(stderr, "cannot write %s\n", out_file);
      if (f) std::fclose(f);
      return 1;
    }
    std::fclose(f);
  }
  return 0;
}
