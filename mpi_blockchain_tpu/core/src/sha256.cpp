#include "sha256.hpp"

#include <cstring>

namespace chaincore {

namespace {

const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

inline uint32_t load_be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

inline void store_be32(uint8_t* p, uint32_t v) {
  p[0] = uint8_t(v >> 24);
  p[1] = uint8_t(v >> 16);
  p[2] = uint8_t(v >> 8);
  p[3] = uint8_t(v);
}

}  // namespace

const uint32_t SHA256_IV[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                               0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};

void sha256_compress(uint32_t state[8], const uint32_t win[16]) {
  uint32_t w[64];
  std::memcpy(w, win, 16 * sizeof(uint32_t));
  for (int i = 16; i < 64; ++i) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; ++i) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint32_t state[8];
  std::memcpy(state, SHA256_IV, sizeof(state));

  size_t off = 0;
  uint32_t w[16];
  while (len - off >= 64) {
    for (int i = 0; i < 16; ++i) w[i] = load_be32(data + off + 4 * i);
    sha256_compress(state, w);
    off += 64;
  }
  // Final padded block(s): remaining bytes + 0x80 + zeros + 64-bit BE length.
  uint8_t tail[128];
  size_t rem = len - off;
  std::memset(tail, 0, sizeof(tail));
  std::memcpy(tail, data + off, rem);
  tail[rem] = 0x80;
  size_t total = (rem + 9 <= 64) ? 64 : 128;
  uint64_t bitlen = uint64_t(len) * 8;
  for (int i = 0; i < 8; ++i)
    tail[total - 1 - i] = uint8_t(bitlen >> (8 * i));
  for (size_t blk = 0; blk < total; blk += 64) {
    for (int i = 0; i < 16; ++i) w[i] = load_be32(tail + blk + 4 * i);
    sha256_compress(state, w);
  }
  for (int i = 0; i < 8; ++i) store_be32(out + 4 * i, state[i]);
}

void sha256d(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint8_t inner[32];
  sha256(data, len, inner);
  sha256(inner, 32, out);
}

void header_midstate(const uint8_t header80[80], uint32_t out_state[8],
                     uint32_t out_tail_w[16]) {
  std::memcpy(out_state, SHA256_IV, 8 * sizeof(uint32_t));
  uint32_t w[16];
  for (int i = 0; i < 16; ++i) w[i] = load_be32(header80 + 4 * i);
  sha256_compress(out_state, w);
  // Chunk 2: header bytes 64..79, 0x80 pad, zeros, 640-bit length.
  for (int i = 0; i < 4; ++i) out_tail_w[i] = load_be32(header80 + 64 + 4 * i);
  out_tail_w[4] = 0x80000000u;
  for (int i = 5; i < 15; ++i) out_tail_w[i] = 0;
  out_tail_w[15] = 80 * 8;
}

void sha256d_from_midstate(const uint32_t midstate[8],
                           const uint32_t tail_w[16], uint8_t out[32]) {
  uint32_t state[8];
  std::memcpy(state, midstate, sizeof(state));
  sha256_compress(state, tail_w);
  // Second hash: the 32-byte digest is one padded chunk. The digest bytes are
  // the big-endian encoding of `state`, so reading them back as big-endian
  // words reproduces `state` directly — no byte swaps needed.
  uint32_t w2[16];
  for (int i = 0; i < 8; ++i) w2[i] = state[i];
  w2[8] = 0x80000000u;
  for (int i = 9; i < 15; ++i) w2[i] = 0;
  w2[15] = 32 * 8;
  uint32_t st2[8];
  std::memcpy(st2, SHA256_IV, sizeof(st2));
  sha256_compress(st2, w2);
  for (int i = 0; i < 8; ++i) store_be32(out + 4 * i, st2[i]);
}

uint64_t midstate_sweep(const uint8_t header80[80], uint64_t start_nonce,
                        uint64_t count, uint32_t difficulty_bits,
                        uint64_t* hashes_tried) {
  uint32_t midstate[8], tail[16];
  header_midstate(header80, midstate, tail);
  uint64_t end = start_nonce + count;
  if (end > 0x100000000ULL) end = 0x100000000ULL;
  uint64_t tried = 0;
  for (uint64_t n = start_nonce; n < end; ++n, ++tried) {
    // The header stores the nonce little-endian; SHA words are big-endian
    // reads of the stream, so word 3 = bswap32(nonce).
    tail[3] = ((uint32_t(n) & 0xff) << 24) | ((uint32_t(n) & 0xff00) << 8) |
              ((uint32_t(n) >> 8) & 0xff00) | (uint32_t(n) >> 24);
    uint8_t digest[32];
    sha256d_from_midstate(midstate, tail, digest);
    if (leading_zero_bits(digest) >= int(difficulty_bits)) {
      if (hashes_tried) *hashes_tried = tried + 1;
      return n;
    }
  }
  if (hashes_tried) *hashes_tried = tried;
  return UINT64_MAX;
}

int leading_zero_bits(const uint8_t h[32]) {
  int bits = 0;
  for (int i = 0; i < 32; ++i) {
    if (h[i] == 0) {
      bits += 8;
      continue;
    }
    uint8_t b = h[i];
    while (!(b & 0x80)) {
      ++bits;
      b <<= 1;
    }
    return bits;
  }
  return bits;
}

}  // namespace chaincore
