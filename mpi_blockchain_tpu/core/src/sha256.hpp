// SHA-256 (FIPS 180-4) primitives for the chain core.
//
// Rebuild of the reference's hashing layer (SURVEY.md §1 layer 1; the
// reference mount was empty this round, so parity is to the BASELINE.json
// capability contract: "double-SHA256 over the block header").
//
// Exposes the raw compression function and midstate helpers so the CPU miner
// and the TPU (JAX/Pallas) backend can share the exact same two-compression
// per-nonce schedule: the 80-byte header occupies two 512-bit chunks, the
// nonce lives in the second chunk, so chunk-1 state ("midstate") is constant
// per candidate header.
#pragma once
#include <cstdint>
#include <cstddef>

namespace chaincore {

// One SHA-256 compression round over a 16-word big-endian message block.
// `state` is updated in place. `w` is the 16-word message block (already
// big-endian words, i.e. bytes loaded MSB-first).
void sha256_compress(uint32_t state[8], const uint32_t w[16]);

// Full SHA-256 of an arbitrary byte message.
void sha256(const uint8_t* data, size_t len, uint8_t out[32]);

// Double SHA-256: sha256(sha256(data)).
void sha256d(const uint8_t* data, size_t len, uint8_t out[32]);

// The SHA-256 initial hash value (H0..H7).
extern const uint32_t SHA256_IV[8];

// Midstate for an 80-byte block header:
//   out_state  = compression state after chunk 1 (header bytes 0..63)
//   out_tail_w = the 16 big-endian words of chunk 2 (header bytes 64..79,
//                then 0x80 pad, zeros, and the 640-bit length), with the
//                nonce word (index 3) taken from the header as-is.
// Per-nonce work is then: replace word 3 with bswap32(nonce), one
// compression from out_state, then one compression for the second hash.
void header_midstate(const uint8_t header80[80], uint32_t out_state[8],
                     uint32_t out_tail_w[16]);

// Finish a double-SHA256 given a midstate and chunk-2 words (word 3 = the
// byte-swapped nonce). Writes the 32-byte final digest.
void sha256d_from_midstate(const uint32_t midstate[8], const uint32_t tail_w[16],
                           uint8_t out[32]);

// Number of leading zero bits of a 32-byte digest interpreted as a 256-bit
// big-endian integer (the proof-of-work difficulty measure).
int leading_zero_bits(const uint8_t h[32]);

// Sequential lowest-nonce-first midstate sweep over [start_nonce,
// start_nonce + count), clamped to the uint32 nonce space. Returns the first
// (== lowest) nonce whose double-SHA256 header hash has >= difficulty_bits
// leading zero bits, or UINT64_MAX if none in range; *hashes_tried (if
// non-null) receives the number of hashes evaluated. This "lowest qualifying
// nonce" rule is the deterministic winner rule every backend implements, so
// CPU and TPU produce identical block hashes. Shared by both Python bindings
// (capi.cpp cc_search and pybind_module.cpp cpu_search).
uint64_t midstate_sweep(const uint8_t header80[80], uint64_t start_nonce,
                        uint64_t count, uint32_t difficulty_bits,
                        uint64_t* hashes_tried);

}  // namespace chaincore
