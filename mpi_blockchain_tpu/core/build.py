"""Builds the C++ core on demand (first import) via the Makefile.

Two binding artifacts:
  libchaincore.so        — C ABI for the ctypes fallback binding
  chaincore_pb<ext>.so   — pybind11 extension (the north-star's spec'd
                           mechanism), buildable because this image vendors
                           pybind11 headers inside the torch / tensorflow
                           include trees (header-only, framework-agnostic).
"""
from __future__ import annotations

import importlib.util
import pathlib
import subprocess
import sysconfig

_CORE_DIR = pathlib.Path(__file__).resolve().parent
_LIB = _CORE_DIR / "libchaincore.so"
_SRC = _CORE_DIR / "src"


def _stale(artifact: pathlib.Path) -> bool:
    if not artifact.exists():
        return True
    mtime = artifact.stat().st_mtime
    return any(p.stat().st_mtime > mtime for p in _SRC.iterdir())


def _fault_check(site: str, **ctx) -> None:
    """native.load injection hook. This module is sometimes loaded
    standalone (spec_from_file_location, no parent package — the
    binding-fallback test does), where relative imports cannot resolve;
    fall back to the absolute form."""
    try:
        from ..resilience import injection
    except ImportError:
        from mpi_blockchain_tpu.resilience import injection
    injection.check(site, **ctx)


def ensure_built() -> pathlib.Path:
    """Compiles the ctypes C ABI library if missing or out of date."""
    _fault_check("native.load", artifact="libchaincore")
    if _stale(_LIB):
        subprocess.run(["make", "-s"], cwd=_CORE_DIR, check=True)
    return _LIB


def find_pybind11_include() -> str:
    """Locates pybind11 headers: a real install, else torch/tf's vendored
    copy (found via find_spec — no heavy framework import)."""
    try:
        import pybind11
        return pybind11.get_include()
    except ImportError:
        pass
    candidates = []
    for pkg, subdirs in (("torch", ("include",)),
                         ("tensorflow",
                          ("include/external/pybind11/include",))):
        spec = importlib.util.find_spec(pkg)
        if spec and spec.submodule_search_locations:
            for base in spec.submodule_search_locations:
                candidates += [pathlib.Path(base) / s for s in subdirs]
    for inc in candidates:
        if (inc / "pybind11" / "pybind11.h").exists():
            return str(inc)
    raise FileNotFoundError(
        "no pybind11 headers found (checked pip install + torch/tensorflow "
        "vendored include trees)")


def pybind_module_path() -> pathlib.Path:
    return _CORE_DIR / ("chaincore_pb"
                        + sysconfig.get_config_var("EXT_SUFFIX"))


def ensure_pybind_built():
    """Builds (if needed) and imports the pybind11 extension module.

    Raises on any failure — the caller (core/__init__.py) decides whether
    to fall back to ctypes or surface the error (MBT_BINDING=pybind11).
    A ``native.load`` fault here exercises exactly that auto-fallback
    seam: the injected failure must degrade to ctypes loudly, not die.
    """
    _fault_check("native.load", artifact="chaincore_pb")
    path = pybind_module_path()
    if _stale(path):
        subprocess.run(
            ["make", "-s", "pybind",
             f"PY_INC={sysconfig.get_paths()['include']}",
             f"PB_INC={find_pybind11_include()}",
             f"EXT_SUFFIX={sysconfig.get_config_var('EXT_SUFFIX')}"],
            cwd=_CORE_DIR, check=True)
    spec = importlib.util.spec_from_file_location("chaincore_pb", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
