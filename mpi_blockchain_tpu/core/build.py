"""Builds libchaincore.so on demand (first import) via the Makefile."""
from __future__ import annotations

import pathlib
import subprocess

_CORE_DIR = pathlib.Path(__file__).resolve().parent
_LIB = _CORE_DIR / "libchaincore.so"
_SRC = _CORE_DIR / "src"


def ensure_built() -> pathlib.Path:
    """Compiles the C++ core if the .so is missing or older than any source."""
    if _LIB.exists():
        lib_mtime = _LIB.stat().st_mtime
        stale = any(p.stat().st_mtime > lib_mtime for p in _SRC.iterdir())
        if not stale:
            return _LIB
    subprocess.run(["make", "-s"], cwd=_CORE_DIR, check=True)
    return _LIB
