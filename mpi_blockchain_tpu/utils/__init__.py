"""Shared utilities: structured logging, profiling, checkpointing."""
from .logging import block_logger, get_logger  # noqa: F401
