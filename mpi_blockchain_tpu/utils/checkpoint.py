"""Chain checkpoint/resume (SURVEY.md §5).

The reference has no persistence; the rebuild adds it so the 1000-block
bench is restartable. A checkpoint is the chain's canonical wire format
(concatenated 80-byte headers — the same bytes Chain::save emits and the
adopt_chain RPC uses) plus a JSON sidecar with the config, so resume can
refuse a difficulty mismatch instead of silently mining an invalid suffix.
There is no device state to checkpoint: the search is stateless per block.
"""
from __future__ import annotations

import dataclasses
import json
import pathlib

from .. import core
from ..config import ConfigError, MinerConfig


def save_chain(node: core.Node, path: str | pathlib.Path,
               config: MinerConfig | None = None) -> None:
    path = pathlib.Path(path)
    path.write_bytes(node.save())
    meta = {"height": node.height, "tip_hash": node.tip_hash.hex(),
            "difficulty_bits": node.difficulty_bits}
    if config is not None:
        meta["config"] = dataclasses.asdict(config)
    path.with_suffix(path.suffix + ".json").write_text(
        json.dumps(meta, sort_keys=True))


def load_chain(path: str | pathlib.Path, difficulty_bits: int,
               node_id: int = 0) -> core.Node:
    """Restores a Node from a checkpoint, re-validating every block."""
    path = pathlib.Path(path)
    sidecar = path.with_suffix(path.suffix + ".json")
    if sidecar.exists():
        try:
            meta = json.loads(sidecar.read_text())
        except json.JSONDecodeError as e:
            raise ConfigError(
                f"corrupt checkpoint sidecar {sidecar}: {e}") from e
        if meta.get("difficulty_bits") != difficulty_bits:
            raise ConfigError(
                f"checkpoint difficulty {meta.get('difficulty_bits')} != "
                f"requested {difficulty_bits}")
    node = core.Node(difficulty_bits, node_id)
    if not node.load(path.read_bytes()):
        raise ConfigError(f"invalid or corrupt chain checkpoint: {path}")
    return node
