"""Crash-safe chain checkpoint/resume (SURVEY.md §5, ISSUE 5).

A checkpoint is the chain's canonical wire format (concatenated 80-byte
headers — the same bytes ``Chain::save`` emits and the adopt_chain RPC
uses) plus an integrity trailer and a JSON sidecar with the config, so
resume can refuse a difficulty mismatch instead of silently mining an
invalid suffix. There is no device state to checkpoint: the search is
stateless per block.

Crash-safety contract (v2, this module's rewrite):

* **Atomic writes.** Payload and sidecar are written tmp → flush →
  fsync → ``os.replace`` (+ best-effort directory fsync), so a crash
  mid-save leaves the PREVIOUS checkpoint intact, never a torn file at
  the published path.
* **Torn writes are detectable and loudly rejected.** The payload
  carries a 48-byte trailer — ``MBTCKPT\\x01`` magic + u64 payload
  length + SHA-256(payload). ``load_chain`` refuses on any mismatch
  (CheckpointError), and a v2 sidecar without an intact trailer is
  itself proof of a tear. The seed bug this kills: a truncated file
  whose length happened to be a multiple of 80 used to load as a
  silently SHORTER chain.
* **Recovery truncates to the last valid block.** ``recover_chain``
  (the ``mine --resume`` path) drops the torn tail, re-validates the
  longest loadable header prefix, rewrites the repaired checkpoint
  atomically, and reports what it dropped — so a SIGKILL'd miner
  resumes instead of dying on its own artifact.
* **Legacy files still load.** A pre-v2 checkpoint (no trailer, no v2
  sidecar) validates through the C++ loader as before.

Fault-injection sites ``checkpoint.write`` / ``checkpoint.read`` let a
fault plan produce torn, bitrotted, or unreadable checkpoints
deterministically (docs/resilience.md).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pathlib
import struct

from .. import core
from ..config import ConfigError, MinerConfig
from ..resilience import injection

MAGIC = b"MBTCKPT\x01"
TRAILER_SIZE = len(MAGIC) + 8 + 32   # magic + u64 payload_len + sha256
SIDECAR_VERSION = 2


class CheckpointError(ConfigError):
    """Integrity failure: torn write, bitrot, or an invalid chain. A
    subclass of ConfigError so the CLI's clean-error contract and every
    pre-existing ``except ValueError`` site keep holding; kept separate
    so ``recover_chain`` can distinguish 'damaged artifact' (recover)
    from 'wrong config' (refuse)."""


def _sidecar_path(path: pathlib.Path) -> pathlib.Path:
    return path.with_suffix(path.suffix + ".json")


def _atomic_write(path: pathlib.Path, data: bytes,
                  fsync: bool = True) -> None:
    """tmp + flush + fsync + rename: the published path only ever holds
    a complete artifact. The pid suffix keeps two processes saving to
    the same path from clobbering each other's tmp."""
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        try:
            dfd = os.open(path.parent, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            # Directory fsync is best-effort (not all filesystems allow
            # it); the rename itself is already atomic.
            return


def seal(payload: bytes) -> bytes:
    """Payload + the integrity trailer ``load_chain`` verifies."""
    return payload + MAGIC + struct.pack("<Q", len(payload)) + \
        hashlib.sha256(payload).digest()


def split_trailer(blob: bytes) -> tuple[bytes, bool]:
    """Splits a checkpoint blob into (payload, verified).

    ``verified`` is True when an intact trailer authenticated the
    payload; False when no trailer is present (a legacy file — or a
    tear, which the sidecar disambiguates). A PRESENT-but-inconsistent
    trailer raises: that can only be corruption.
    """
    if len(blob) >= TRAILER_SIZE and \
            blob[-TRAILER_SIZE:-40] == MAGIC:
        payload = blob[:-TRAILER_SIZE]
        length, = struct.unpack("<Q", blob[-40:-32])
        digest = blob[-32:]
        if length != len(payload) or \
                hashlib.sha256(payload).digest() != digest:
            raise CheckpointError(
                "checkpoint trailer mismatch (torn write or bitrot): "
                f"trailer claims {length} payload bytes, "
                f"file holds {len(payload)}")
        return payload, True
    return blob, False


def save_chain(node: core.Node, path: str | pathlib.Path,
               config: MinerConfig | dict | None = None,
               fsync: bool = True,
               mesh: dict | None = None) -> pathlib.Path:
    """Atomically writes the chain checkpoint + sidecar; returns path.
    ``config`` may be a MinerConfig or an already-serialized config dict
    (the recovery rewrite preserves the original sidecar's). ``mesh``
    is the elastic world's membership payload (world_size / live /
    evicted — resilience/elastic.ElasticWorld.membership): it rides the
    sealed sidecar so ``--resume`` restores the SHRUNKEN world instead
    of re-assuming the seed world (docs/resilience.md §Elastic mesh)."""
    from ..resilience import FaultInjected
    from ..telemetry import counter
    from ..telemetry.events import emit_event

    path = pathlib.Path(path)
    payload = node.save()
    blob = seal(payload)
    fault = injection.check("checkpoint.write", path=str(path),
                            height=node.height)
    if fault is not None and fault.kind == "partial":
        # The injected torn write: bypass the atomic path and publish a
        # truncated artifact directly — exactly the on-disk state a
        # crash mid-write used to leave — then die like the crash would.
        with open(path, "wb") as f:
            f.write(blob[:max(1, len(blob) // 2)])
        raise FaultInjected("checkpoint.write", "partial",
                            fault.message or f"torn checkpoint write "
                            f"at {path}")
    meta = {"checkpoint_version": SIDECAR_VERSION,
            "height": node.height, "tip_hash": node.tip_hash.hex(),
            "difficulty_bits": node.difficulty_bits,
            "payload_len": len(payload),
            "payload_sha256": hashlib.sha256(payload).hexdigest()}
    if config is not None:
        meta["config"] = (config if isinstance(config, dict)
                          else dataclasses.asdict(config))
    if mesh is not None:
        meta["mesh"] = dict(mesh)
    _atomic_write(path, blob, fsync=fsync)
    _atomic_write(_sidecar_path(path),
                  json.dumps(meta, sort_keys=True).encode(), fsync=fsync)
    if fault is not None and fault.kind == "corrupt":
        # Injected bitrot: flip one payload byte of the PUBLISHED file
        # (after a clean write — rot happens at rest, not in flight).
        rotted = bytearray(path.read_bytes())
        rotted[len(rotted) // 2] ^= 0xFF
        path.write_bytes(bytes(rotted))
    counter("checkpoints_saved_total",
            help="chain checkpoints written (atomic, sealed)").inc()
    emit_event({"event": "checkpoint_saved", "height": node.height,
                "path": str(path)})
    return path


def _sidecar_version(meta: dict) -> int:
    """The sidecar's checkpoint_version as an int; a non-numeric value
    is sidecar corruption (loud CheckpointError, so recover_chain can
    still salvage an intact payload), never a TypeError."""
    v = meta.get("checkpoint_version", 1)
    try:
        return int(v)
    except (TypeError, ValueError):
        raise CheckpointError(
            f"corrupt checkpoint sidecar: non-numeric "
            f"checkpoint_version {v!r}") from None


def _read_sidecar(path: pathlib.Path) -> dict | None:
    sidecar = _sidecar_path(path)
    if not sidecar.exists():
        return None
    try:
        meta = json.loads(sidecar.read_text())
    except json.JSONDecodeError as e:
        raise CheckpointError(
            f"corrupt checkpoint sidecar {sidecar}: {e}") from e
    if not isinstance(meta, dict):
        raise CheckpointError(f"corrupt checkpoint sidecar {sidecar}: "
                              f"not a JSON object")
    return meta


def open_checkpoint(path: str | pathlib.Path,
                    blob: bytes | None = None
                    ) -> tuple[bytes, bool, dict | None]:
    """Integrity-checks a checkpoint blob against its trailer AND its
    sidecar; returns (payload, sealed, sidecar_meta). The shared gate
    for ``load_chain`` and ``verify --chain``: a v2 sidecar whose
    trailer is gone (the tear that lands exactly on the trailer
    boundary) or whose digest disagrees raises here — neither reader
    may bless a torn artifact as a valid shorter chain. Legacy files
    (no trailer, no v2 sidecar) pass through unsealed."""
    path = pathlib.Path(path)
    if blob is None:
        blob = path.read_bytes()
    meta = _read_sidecar(path)
    payload, verified = split_trailer(blob)
    sealed_meta = meta is not None and (
        _sidecar_version(meta) >= 2 or "payload_sha256" in meta)
    if sealed_meta and not verified:
        raise CheckpointError(
            f"torn checkpoint write detected: sidecar declares a sealed "
            f"v{_sidecar_version(meta)} checkpoint but {path} "
            f"has no intact trailer")
    if sealed_meta and meta.get("payload_sha256") != \
            hashlib.sha256(payload).hexdigest():
        raise CheckpointError(
            f"checkpoint payload digest does not match its sidecar: "
            f"{path} (torn write or bitrot)")
    if not payload or len(payload) % core.HEADER_SIZE:
        raise CheckpointError(
            f"torn or empty chain checkpoint: {path} holds "
            f"{len(payload)} bytes, not a whole number of "
            f"{core.HEADER_SIZE}-byte headers")
    return payload, verified, meta


def load_chain(path: str | pathlib.Path, difficulty_bits: int,
               node_id: int = 0) -> core.Node:
    """Restores a Node from a checkpoint, verifying integrity end to
    end: trailer (or sidecar-declared trailer absence = tear), sidecar
    digest, difficulty, then full C++ re-validation of every block."""
    path = pathlib.Path(path)
    fault = injection.check("checkpoint.read", path=str(path))
    blob = path.read_bytes()
    if fault is not None:
        if fault.kind == "corrupt":
            rotted = bytearray(blob)
            rotted[len(rotted) // 2] ^= 0xFF
            blob = bytes(rotted)
        elif fault.kind == "partial":
            blob = blob[:max(1, len(blob) // 2)]
    meta = _read_sidecar(path)
    if meta is not None and meta.get("difficulty_bits") != difficulty_bits:
        raise ConfigError(
            f"checkpoint difficulty {meta.get('difficulty_bits')} != "
            f"requested {difficulty_bits}")
    payload, _, _ = open_checkpoint(path, blob)
    node = core.Node(difficulty_bits, node_id)
    if not node.load(payload):
        raise CheckpointError(f"invalid or corrupt chain checkpoint: "
                              f"{path}")
    return node


def recover_chain(path: str | pathlib.Path, difficulty_bits: int,
                  node_id: int = 0) -> tuple[core.Node, dict]:
    """``mine --resume``'s loader: load, or truncate a torn tail to the
    last valid block and load THAT.

    Only integrity damage (CheckpointError) triggers recovery; a
    difficulty mismatch or unreadable file still refuses — recovering
    from a *wrong* checkpoint would be the silent-corruption bug this
    module exists to kill. On recovery the repaired checkpoint is
    rewritten atomically so the next resume is clean, and the report
    says exactly what was dropped.
    """
    from ..telemetry import counter
    from ..telemetry.events import emit_event

    path = pathlib.Path(path)
    try:
        node = load_chain(path, difficulty_bits, node_id)
        # load_chain already validated the sidecar, so this re-read
        # cannot raise; the mesh membership (if any) travels with the
        # report so --resume can restore a shrunken elastic world.
        meta = _read_sidecar(path) or {}
        return node, {"recovered": False, "height": node.height,
                      "dropped_bytes": 0, "mesh": meta.get("mesh")}
    except CheckpointError as damage:
        blob = path.read_bytes()
        try:
            payload, _ = split_trailer(blob)
        except CheckpointError:
            # A PRESENT-but-inconsistent trailer is still metadata, not
            # chain bytes: strip it so dropped_bytes counts only chain
            # data (a digest-only bitrot must report 0 bytes lost).
            payload = blob
            if len(blob) >= TRAILER_SIZE and \
                    blob[-TRAILER_SIZE:-40] == MAGIC:
                payload = blob[:-TRAILER_SIZE]
        try:
            meta = _read_sidecar(path) or {}
        except CheckpointError:
            meta = {}        # sidecar itself corrupt: nothing to keep
        config = meta.get("config")
        mesh_meta = meta.get("mesh")
        usable = payload[:len(payload) - len(payload) % core.HEADER_SIZE]
        for k in range(len(usable) // core.HEADER_SIZE, 0, -1):
            node = core.Node(difficulty_bits, node_id)
            if node.load(usable[:k * core.HEADER_SIZE]):
                # Chain bytes actually lost — measured against the
                # PAYLOAD, not the raw blob (the 48-byte trailer is
                # metadata; counting it would report a spurious tear
                # when only the seal was damaged).
                dropped = len(payload) - k * core.HEADER_SIZE
                counter("checkpoint_recoveries_total",
                        help="torn checkpoints truncated to their last "
                             "valid block on resume").inc()
                emit_event({"event": "checkpoint_truncated",
                            "path": str(path), "height": node.height,
                            "dropped_bytes": dropped,
                            "damage": str(damage)})
                # Rewrite the repaired artifact, preserving the original
                # sidecar's recorded run config AND elastic mesh
                # membership when they survived.
                save_chain(node, path, config, mesh=mesh_meta)
                return node, {"recovered": True, "height": node.height,
                              "dropped_bytes": dropped,
                              "mesh": mesh_meta}
        raise
