"""Structured logging (SURVEY.md §5 metrics/observability).

The reference's std::cout prints become JSON-lines records: one dict per
mined block {height, nonce, hash, wall_ms, hashes_tried}, emitted through
Python logging so callers can redirect or silence them.
"""
from __future__ import annotations

import logging
from typing import Callable

_LOGGER_NAME = "mpi_blockchain_tpu"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter("%(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def block_logger() -> Callable[[dict], None]:
    """Returns a callable that logs one structured record as a JSON line.

    Delegates to the telemetry JSON-lines event stream, which logs at
    INFO — the level ``get_logger()`` actually enables. (The original
    implementation logged at DEBUG under the INFO logger, silently
    dropping every per-block record; ``tests/test_telemetry.py`` holds
    the regression.) Kept as the stable seam Miner/FusedMiner inject.
    """
    from ..telemetry.events import emit_event

    return emit_event
