"""Structured logging (SURVEY.md §5 metrics/observability).

The reference's std::cout prints become JSON-lines records: one dict per
mined block {height, nonce, hash, wall_ms, hashes_tried}, emitted through
Python logging so callers can redirect or silence them.
"""
from __future__ import annotations

import json
import logging
from typing import Callable

_LOGGER_NAME = "mpi_blockchain_tpu"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter("%(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def block_logger() -> Callable[[dict], None]:
    """Returns a callable that logs one structured record as a JSON line."""
    logger = get_logger()

    def log(record: dict) -> None:
        logger.debug(json.dumps(record, sort_keys=True))

    return log
