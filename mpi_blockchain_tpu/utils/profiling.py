"""Profiling hooks (SURVEY.md §5 tracing).

Wraps jax.profiler so a mining run can capture a perfetto-compatible device
trace of the sweep kernels:

    with trace_mining("/tmp/trace"):
        miner.mine_chain(10)

View with ui.perfetto.dev or tensorboard --logdir. While the capture is
active, the telemetry span bridge is enabled: every host-side telemetry
span (miner.sweep, backend.tpu.dispatch, ...) enters a
``jax.profiler.TraceAnnotation``, so the host timeline nests alongside
the device kernels in the same trace.

Hardened: the logdir is created if missing, ``create_perfetto_link`` is
passed through to ``start_trace``, and a missing/stripped jax.profiler
turns the whole context into a warned no-op instead of an exception —
profiling must never take down a mining run.
"""
from __future__ import annotations

import contextlib
import os
import warnings


@contextlib.contextmanager
def trace_mining(logdir: str, create_perfetto_link: bool = False):
    try:
        import jax

        profiler = jax.profiler
        profiler.start_trace  # noqa: B018  probe before committing
    except (ImportError, AttributeError) as e:
        warnings.warn(f"jax.profiler unavailable ({e!r}); trace_mining "
                      f"is a no-op", RuntimeWarning, stacklevel=3)
        yield
        return

    from ..telemetry import spans as _spans

    os.makedirs(logdir, exist_ok=True)
    profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    bridged = _spans.enable_perfetto()
    try:
        yield
    finally:
        if bridged:
            _spans.disable_perfetto()
        profiler.stop_trace()
