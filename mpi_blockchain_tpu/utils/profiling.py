"""Profiling hooks (SURVEY.md §5 tracing).

Wraps jax.profiler so a mining run can capture a perfetto-compatible device
trace of the sweep kernels:

    with trace_mining("/tmp/trace"):
        miner.mine_chain(10)

View with ui.perfetto.dev or tensorboard --logdir.
"""
from __future__ import annotations

import contextlib


@contextlib.contextmanager
def trace_mining(logdir: str):
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
