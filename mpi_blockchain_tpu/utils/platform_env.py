"""The forced virtual-CPU-mesh environment recipe, in ONE place.

The axon TPU site-hook re-forces JAX_PLATFORMS=axon, so switching a process
to the virtual CPU mesh takes BOTH halves of this recipe (discovered the
hard way in round 1 — see tests/conftest.py and VERDICT r1 item 1):

  1. before the first jax import: env vars from :func:`force_cpu_mesh_env`;
  2. after it: ``jax.config.update("jax_platforms", "cpu")`` — the config
     knob is what actually beats the site-hook.

Importing this module must stay cheap and jax-free: callers build child
environments before any device init.
"""
from __future__ import annotations


def force_cpu_mesh_env(env: dict, n_devices: int) -> dict:
    """A copy of `env` forcing an n_devices virtual CPU platform."""
    out = dict(env)
    out["JAX_PLATFORMS"] = "cpu"
    out["XLA_FLAGS"] = (out.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count="
                          f"{n_devices}")
    return out
